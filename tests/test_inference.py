"""Inference Config/create_predictor (reference strategy:
inference/tests/api exercise AnalysisPredictor configs end-to-end; the
int8 tests compare quantized outputs against fp32 within calibrated
tolerance — mkldnn_quantizer_tester.cc pattern)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.contrib.quant import PTQ
from paddle_tpu.inference import Config, PrecisionType, create_predictor


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 8)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _calibrated(model, batches=4):
    """PTQ-calibrate and return the activation scales keyed like
    named_sublayers."""
    ptq = PTQ()
    ptq.quantize(model)
    rng = np.random.RandomState(0)
    for _ in range(batches):
        model(paddle.to_tensor(rng.randn(8, 16).astype(np.float32)))
    return {name: {"activation": s}
            for name, s in ptq.scales().items()}


class TestSavedProgramPath:
    def test_native_precision_runs_saved_artifact(self, tmp_path):
        paddle.seed(0)
        model = MLP()
        x = paddle.to_tensor(np.ones((4, 16), np.float32))
        ref = np.asarray(model(x).data)
        path = str(tmp_path / "mlp")
        paddle.jit.save(model, path, example_inputs=[x])

        pred = create_predictor(Config(path))
        out = pred.run(np.ones((4, 16), np.float32))
        np.testing.assert_allclose(np.asarray(out.data), ref, atol=1e-6)

    def test_precision_override_requires_layer(self, tmp_path):
        paddle.seed(0)
        model = MLP()
        x = paddle.to_tensor(np.ones((4, 16), np.float32))
        path = str(tmp_path / "mlp")
        paddle.jit.save(model, path, example_inputs=[x])
        cfg = Config(path).set_precision(PrecisionType.Bfloat16)
        with pytest.raises(ValueError, match="set_model"):
            create_predictor(cfg)


class TestPrecision:
    def test_bf16_predictor(self, tmp_path):
        paddle.seed(1)
        model = MLP()
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        ref = np.asarray(model(paddle.to_tensor(x)).data)
        path = str(tmp_path / "m")
        paddle.jit.save(model, path,
                        example_inputs=[paddle.to_tensor(x)])

        cfg = Config(path).set_precision(PrecisionType.Bfloat16)
        cfg.set_model(MLP())
        out = create_predictor(cfg).run(x)
        np.testing.assert_allclose(np.asarray(out.data).astype(np.float32),
                                   ref, rtol=0.05, atol=0.05)

    def test_int8_predictor_matches_fp32_within_tolerance(self):
        paddle.seed(2)
        model = MLP()
        rng = np.random.RandomState(1)
        x = rng.randn(8, 16).astype(np.float32)
        ref = np.asarray(model(paddle.to_tensor(x)).data)

        scales = _calibrated(MLP_copy(model))
        cfg = Config().set_model(model)
        cfg.enable_int8(scales)
        pred = create_predictor(cfg)
        out = np.asarray(pred.run(x).data)
        # int8 quantization error bound: relative to output range
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
        assert err < 0.1, err

    def test_int8_requires_scales(self):
        model = MLP()
        cfg = Config().set_model(model)
        cfg.enable_int8({})
        with pytest.raises(ValueError, match="activation scale"):
            create_predictor(cfg)

    def test_int8_scales_from_json(self, tmp_path):
        import json

        paddle.seed(4)
        model = MLP()
        scales = _calibrated(MLP_copy(model))
        p = tmp_path / "scales.json"
        p.write_text(json.dumps(scales))
        cfg = Config().set_model(model)
        cfg.enable_int8(str(p))
        pred = create_predictor(cfg)
        out = pred.run(np.ones((2, 16), np.float32))
        assert np.isfinite(np.asarray(out.data)).all()


def MLP_copy(model):
    """A weight-sharing copy for calibration (PTQ mutates hooks)."""
    clone = MLP()
    clone.set_state_dict({k: v for k, v in model.state_dict().items()})
    return clone


class TestModelUntouched:
    def test_user_model_keeps_fp32_behavior_after_int8_build(self):
        """create_predictor must not permanently monkey-patch the user's
        layers: model(x) outside the predictor stays fp32-exact."""
        paddle.seed(7)
        model = MLP()
        x = np.random.RandomState(5).randn(4, 16).astype(np.float32)
        ref = np.asarray(model(paddle.to_tensor(x)).data)

        scales = _calibrated(MLP_copy(model))
        cfg = Config().set_model(model)
        cfg.enable_int8(scales)
        pred = create_predictor(cfg)
        _ = pred.run(x)                       # traces with patches active
        after = np.asarray(model(paddle.to_tensor(x)).data)
        np.testing.assert_allclose(after, ref, atol=1e-6)
        # and the predictor still serves int8 after the direct call
        out = np.asarray(pred.run(x).data)
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
        assert 0 < err < 0.1


class TestRootLinearInt8:
    def test_model_that_is_itself_a_linear(self):
        """ADVICE r4: a model whose ONLY Linear is the top-level layer
        must quantize (named_sublayers defaults exclude self)."""
        import paddle_tpu.nn as nn

        paddle.seed(5)
        model = nn.Linear(16, 4)
        rng = np.random.RandomState(3)
        x = rng.randn(8, 16).astype(np.float32)
        ref = np.asarray(model(paddle.to_tensor(x)).data)

        from paddle_tpu.contrib.quant import PTQ

        calib = nn.Linear(16, 4)
        calib.weight.data = model.weight.data
        calib.bias.data = model.bias.data
        ptq = PTQ()
        ptq.quantize(calib)
        for _ in range(4):
            calib(paddle.to_tensor(rng.randn(8, 16).astype(np.float32)))
        scales = {name: {"activation": s}
                  for name, s in ptq.scales().items()}
        assert "" in scales          # root observed under the empty prefix

        cfg = Config().set_model(model)
        cfg.enable_int8(scales)
        out = np.asarray(create_predictor(cfg).run(x).data)
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
        assert err < 0.1, err
