"""Silent-corruption sentinel tests: parameter-tree fingerprints and
the cross-rank compare, sampled step-replay verification, the bitflip
fault kind, audit-on-save, the param_divergence rewind-and-replay
repair, exporter integration, and the silent-except lint."""
import importlib.util
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.hapi import CheckpointCallback, IntegrityCallback
from paddle_tpu.io import Dataset
from paddle_tpu.observability import (HealthMonitor, MetricsRegistry,
                                      Tracer, default_registry,
                                      start_telemetry_server)
from paddle_tpu.resilience import (CheckpointAuditError,
                                   CheckpointManager, FaultSpec,
                                   SimulatedCrash, injected_faults)
from paddle_tpu.resilience.faults import fault_point
from paddle_tpu.resilience.integrity import (compare_digests,
                                             first_divergent_leaf,
                                             majority_partition,
                                             shard_fingerprint,
                                             tree_fingerprint)

REPO = os.path.join(os.path.dirname(__file__), os.pardir)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ------------------------------------------------------------ fingerprints


class TestTreeFingerprint:
    def test_leaf_paths_and_stability(self):
        tree = {"a": {"w": np.arange(4, dtype=np.float32)},
                "b": [np.ones(2, np.int32), None, 7]}
        fp = tree_fingerprint(tree)
        assert set(fp) == {"a/w", "b/0", "b/2"}    # None leaf skipped
        assert fp == tree_fingerprint(tree)        # deterministic

    def test_shape_and_dtype_ride_in_the_digest(self):
        flat = np.zeros(4, np.float32)
        assert tree_fingerprint({"x": flat}) != \
            tree_fingerprint({"x": flat.reshape(2, 2)})
        assert tree_fingerprint({"x": np.zeros(4, np.float32)}) != \
            tree_fingerprint({"x": np.zeros(8, np.float16)})

    def test_one_bit_changes_the_leaf_digest(self):
        a = np.arange(64, dtype=np.float32)
        b = a.copy()
        b.view(np.uint8)[17] ^= 1
        fa, fb = tree_fingerprint({"w": a}), tree_fingerprint({"w": b})
        assert fa["w"] != fb["w"]
        assert first_divergent_leaf(fa, fb) == "w"

    def test_first_divergent_leaf_counts_missing(self):
        assert first_divergent_leaf({"a": 1, "b": 2}, {"a": 1}) == "b"
        assert first_divergent_leaf({"a": 1}, {"a": 1}) is None

    def test_majority_partition_and_tie_anchor(self):
        good = {"w": 1}
        bad = {"w": 2}
        maj, mino, d = majority_partition({0: good, 1: bad, 2: good})
        assert (maj, mino, d) == ([0, 2], [1], good)
        # 1-vs-1 tie anchors to the group holding the lowest rank
        maj, mino, _ = majority_partition({0: good, 1: bad})
        assert (maj, mino) == ([0], [1])

    def test_compare_digests(self):
        good = {"w": 1, "b": 5}
        bad = {"w": 2, "b": 5}
        assert compare_digests({0: good, 1: good}) is None
        assert compare_digests({0: good}) is None      # nothing to compare
        rep = compare_digests({0: good, 1: bad, 2: good})
        assert rep["divergent_ranks"] == [1]
        assert rep["majority_ranks"] == [0, 2]
        assert rep["first_divergent_leaf"] == {1: "w"}


class TestShardFingerprint:
    """GSPMD shard-view fingerprints on a 2x2 (dp x mp) mesh — the
    multi-chip regression the ROADMAP asked for: the sentinel digests
    each rank's ADDRESSABLE shards and compares only within dp replica
    groups (mp peers hold different windows and legitimately differ)."""

    def _mesh_tree(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paddle_tpu.distributed import mesh as mesh_mod

        mesh = mesh_mod.build_mesh(dp=2, mp=2)
        w = jax.device_put(
            np.arange(64, dtype=np.float32).reshape(8, 8),
            NamedSharding(mesh, P(None, "mp")))
        g = jax.device_put(np.ones(4, np.float32),
                           NamedSharding(mesh, P()))
        return mesh, {"w": w, "g": g}

    def _rank_devices(self, mesh):
        """One simulated process per mesh device of the (dp, mp) grid,
        rank = dp_idx * mp + mp_idx (build_mesh's row-major layout)."""
        grid = mesh.devices.reshape(2, 2)
        return {d * 2 + m: [grid[d, m]] for d in range(2)
                for m in range(2)}

    def test_window_keys_and_dedup(self):
        mesh, tree = self._mesh_tree()
        fp = shard_fingerprint(tree)
        # w: 2 distinct mp windows (dp replicas dedup); g: 1 window
        assert set(fp) == {"w@0:8,0:4", "w@0:8,4:8", "g@0:4"}
        assert fp == shard_fingerprint(tree)

    def test_dp_replicas_match_mp_peers_differ(self):
        from paddle_tpu.distributed.mesh import replica_peers

        mesh, tree = self._mesh_tree()
        devs = self._rank_devices(mesh)
        digests = {r: shard_fingerprint(tree, devices=devs[r])
                   for r in range(4)}
        # dp replicas (ranks differing only in dp coord) are bitwise
        # identical; mp neighbours hold DIFFERENT windows
        axes = {"dp": 2, "mp": 2}
        assert replica_peers(0, axes) == [0, 2]
        assert digests[0] == digests[2]
        assert digests[1] == digests[3]
        assert digests[0] != digests[1]
        # restricted to the dp replica group: no divergence
        assert compare_digests({r: digests[r]
                                for r in replica_peers(0, axes)}) is None
        assert compare_digests({r: digests[r]
                                for r in replica_peers(1, axes)}) is None
        # the naive all-ranks compare would false-positive — exactly
        # why the callback takes peers=
        assert compare_digests(digests) is not None

    def test_corrupt_shard_detected_within_replica_group(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh, tree = self._mesh_tree()
        devs = self._rank_devices(mesh)
        bad = np.asarray(tree["w"]).copy()
        bad[3, 5] += 1e-3                    # lands in the mp=1 window
        tree_bad = {"w": jax.device_put(
            bad, NamedSharding(mesh, P(None, "mp"))), "g": tree["g"]}
        # rank 3 (dp=1, mp=1) computes from the corrupted state
        digests = {1: shard_fingerprint(tree, devices=devs[1]),
                   3: shard_fingerprint(tree_bad, devices=devs[3])}
        rep = compare_digests(digests)
        assert rep is not None
        leaf = list(rep["first_divergent_leaf"].values())[0]
        assert leaf == "w@0:8,4:8"           # names the exact window

    def test_callback_peers_restriction(self):
        """IntegrityCallback wired for the 2x2 mesh: rank 1 publishes
        its mp=1 shard view; rank 3 (its dp replica) sees a match while
        rank 0's digest — present in the store — is never consulted."""
        from paddle_tpu.hapi import IntegrityCallback

        mesh, tree = self._mesh_tree()
        devs = self._rank_devices(mesh)
        store = TCPStore(is_master=True, world_size=1)
        cbs = {}
        for r in (0, 1, 3):
            cb = IntegrityCallback(
                store=store, rank=r, world_size=4,
                fingerprint_every=1, peers=[r % 2, r % 2 + 2],
                fingerprint_shards=True, local_devices=devs[r],
                registry=MetricsRegistry())
            cb._fingerprint_tree = (
                lambda t=tree, rr=r: {"params": t})   # bypass model
            cb.model = None
            cbs[r] = cb
        for r in (0, 1, 3):
            cbs[r]._global_step = 1
            cbs[r]._run_fingerprint(step=0)
        assert cbs[3].divergence_active is False
        assert cbs[3].last_verified_global_step == 1
        assert cbs[1].events == [] and cbs[3].events == []


# --------------------------------------------------------- bitflip fault


def _flip_count(site):
    fam = default_registry().get("faults_injected_total")
    return fam.labels(site=site, kind="bitflip").value if fam else 0


class TestBitflipFault:
    def test_pinned_leaf_and_bit(self):
        orig = np.zeros(8, np.float32)
        tree = {"w": orig, "b": np.ones(2, np.float32)}
        before = _flip_count("t.tree")
        with injected_faults(FaultSpec("t.tree", "bitflip",
                                       leaf="w", bit=3)):
            fault_point("t.tree", tree=tree)
        flipped = np.asarray(tree["w"]).view(np.uint8)
        assert flipped[0] == 1 << 3
        assert flipped[1:].sum() == 0
        np.testing.assert_array_equal(tree["b"], np.ones(2, np.float32))
        # the caller's original array object is never mutated in place —
        # the injector swaps in a corrupted COPY (jax arrays are
        # immutable; the live-tree writeback is the call site's job)
        assert orig.view(np.uint8).sum() == 0
        assert _flip_count("t.tree") == before + 1

    def test_seed_deterministic_choice(self):
        def run():
            tree = {"a": np.zeros(16, np.float32),
                    "b": np.zeros(16, np.float32)}
            with injected_faults(FaultSpec("t.seed", "bitflip"), seed=5):
                fault_point("t.seed", tree=tree)
            return {k: np.asarray(v).tobytes() for k, v in tree.items()}

        one, two = run(), run()
        assert one == two
        assert sum(v != np.zeros(16, np.float32).tobytes()
                   for v in one.values()) == 1

    def test_missing_pinned_leaf_raises(self):
        with injected_faults(FaultSpec("t.miss", "bitflip", leaf="nope")):
            with pytest.raises(KeyError, match="nope"):
                fault_point("t.miss", tree={"w": np.ones(2)})

    def test_file_mode_flips_exactly_one_bit(self, tmp_path):
        p = tmp_path / "blob.bin"
        p.write_bytes(bytes(64))
        with injected_faults(FaultSpec("t.file", "bitflip", bit=9)):
            fault_point("t.file", path=str(p))
        data = p.read_bytes()
        assert data[1] == 1 << 1 and sum(data) == 2

    def test_directory_mode_flips_one_file(self, tmp_path):
        for name in ("a.bin", "b.bin"):
            (tmp_path / name).write_bytes(bytes(32))
        with injected_faults(FaultSpec("t.dir", "bitflip"), seed=0):
            fault_point("t.dir", path=str(tmp_path))
        changed = [n for n in ("a.bin", "b.bin")
                   if (tmp_path / n).read_bytes() != bytes(32)]
        assert len(changed) == 1
        blob = (tmp_path / changed[0]).read_bytes()
        assert bin(int.from_bytes(blob, "big")).count("1") == 1


# ------------------------------------------------------------- fit harness


class _Arrays(Dataset):
    def __init__(self, n=32):
        rng = np.random.RandomState(7)
        self.y = rng.randint(0, 2, (n,)).astype(np.int64)
        self.x = (rng.randn(n, 4) * 0.3
                  + self.y[:, None] * 2.0).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class _Losses(paddle.hapi.Callback):
    def __init__(self):
        super().__init__()
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(logs["loss"])


def _model(seed=11):
    paddle.seed(seed)
    model = paddle.Model(nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                       nn.Linear(8, 2)))
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    return model


def _params_bytes(model):
    return {k: np.asarray(p.data).tobytes()
            for k, p in model.network.named_parameters()}


def _fit(model, callbacks, data=None):
    model.fit(data or _Arrays(), batch_size=4, epochs=1, shuffle=False,
              verbose=0, callbacks=callbacks)


def _rollback_count(reason):
    fam = default_registry().get("training_rollbacks_total")
    return fam.labels(reason=reason).value if fam else 0


# ------------------------------------------------------------ step replay


class TestStepReplay:
    def test_clean_steps_replay_bitwise_identical(self):
        reg = MetricsRegistry()
        cb = IntegrityCallback(replay_every=3, fingerprint_every=0,
                               registry=reg, tracer=Tracer())
        _fit(_model(), [cb])
        assert cb.events == []
        assert cb.checks["replay"] == 2            # steps 3 and 6 of 8
        snap = reg.snapshot()
        assert snap["integrity_replay_seconds"]["value"]["count"] == 2

    def test_corrupted_step_caught_with_first_leaf_named(self):
        """A bitflip injected into the live step's post-update params
        makes the re-executed step disagree — the sentinel reports the
        first differing leaf (this is SDC or nondeterminism, depending
        on which execution you believe; either is a firing offense)."""
        reg = MetricsRegistry()
        mon = HealthMonitor(action="gauge", registry=MetricsRegistry(),
                            tracer=Tracer())
        cb = IntegrityCallback(replay_every=3, fingerprint_every=0,
                               monitor=mon, registry=reg,
                               tracer=Tracer())
        with injected_faults(FaultSpec("hapi.step_params", "bitflip",
                                       occurrence=3, leaf="0.weight",
                                       bit=21)):
            _fit(_model(), [cb, mon])
        assert len(cb.events) == 1
        ev = cb.events[0]
        assert ev["kind"] == "replay"
        assert ev["global_step"] == 3
        assert ev["first_divergent_leaf"] == "0.weight"
        fam = reg.get("integrity_divergence_total")
        assert fam.labels(kind="replay").value == 1
        # the monitor saw it as a (non-rollback) anomaly kind
        assert [k for k, _, _ in mon.events] == ["step_replay_mismatch"]


# --------------------------------------------- cross-rank fingerprints


class TestCrossRankDivergence:
    def _run_ranks(self, tmp_path, corrupt_rank=1, monitor_ranks=(),
                   world=3, occurrence=5, bit=17):
        """Sequential dp replicas sharing one TCPStore: identical seed,
        identical data, per-rank checkpoints.  ``corrupt_rank`` gets a
        bitflip injected into its post-step params at global step
        ``occurrence``.  Returns (callbacks, losses, final params,
        monitors) per rank."""
        store = TCPStore(is_master=True, world_size=1)
        cbs, losses, finals, mons = {}, {}, {}, {}
        for rank in range(world):
            reg = MetricsRegistry()
            mon = None
            if rank in monitor_ranks:
                mon = HealthMonitor(action="rollback", registry=reg,
                                    tracer=Tracer())
            cb = IntegrityCallback(store=store, rank=rank,
                                   world_size=world,
                                   fingerprint_every=2, history=1000,
                                   monitor=mon, registry=reg,
                                   tracer=Tracer())
            rec = _Losses()
            model = _model()
            ck = CheckpointCallback(str(tmp_path / f"ck{rank}"),
                                    every_n_steps=1)
            cblist = [rec, cb, ck] + ([mon] if mon else [])
            if rank == corrupt_rank:
                with injected_faults(
                        FaultSpec("hapi.step_params", "bitflip",
                                  occurrence=occurrence,
                                  leaf="0.weight", bit=bit)):
                    _fit(model, cblist)
            else:
                _fit(model, cblist)
            cbs[rank], losses[rank] = cb, rec.losses
            finals[rank], mons[rank] = _params_bytes(model), mon
        return cbs, losses, finals, mons

    def test_detection_names_rank_and_leaf(self, tmp_path):
        """Detect-only (no monitor): the divergent rank knows it
        diverged, from which leaf, and stays flagged unhealthy."""
        cbs, _, finals, _ = self._run_ranks(tmp_path, world=2)
        assert cbs[0].events == []
        ev = cbs[1].events[0]
        assert ev["kind"] == "cross_rank"
        assert ev["divergent_ranks"] == [1]
        assert ev["first_divergent_leaf"] == {1: "params/0.weight"}
        assert ev["self_divergent"] is True
        assert ev["last_verified_global_step"] == 4    # fp at 2 and 4
        assert ev["global_step"] == 6       # corruption at 5, fp at 6
        # no repair ran: the corruption persists and so does the flag
        assert cbs[1].divergence_active is True
        assert finals[0] != finals[1]

    def test_e2e_bitflip_detected_repaired_bitwise_equal(self, tmp_path):
        """Acceptance: a bitflip in one of 3 dp ranks' params is caught
        by the fingerprint compare within one sampling interval, the
        rank and leaf are named, rollback restores last-verified-good
        state, and the continued curve is bitwise-equal to the ranks
        that never saw the corruption."""
        before = _rollback_count("param_divergence")
        cbs, losses, finals, mons = self._run_ranks(
            tmp_path, monitor_ranks=(0, 1, 2))
        # healthy ranks: clean, and every fingerprint interval verified
        assert cbs[0].events == [] and cbs[2].events == []
        assert cbs[0].last_verified_global_step == 8
        # the divergent rank detected itself at the first fingerprint
        # after the step-5 corruption
        ev = cbs[1].events[0]
        assert ev["divergent_ranks"] == [1]
        assert ev["first_divergent_leaf"] == {1: "params/0.weight"}
        assert _rollback_count("param_divergence") == before + 1
        # rewind-and-replay: steps 5 and 6 trained twice (8 + 2)
        assert len(losses[1]) == 10 and len(losses[0]) == 8
        # the replayed tail is BITWISE equal to the clean rank's curve
        assert losses[1][6:] == losses[0][4:]
        # ...and the final state reconverged bitwise, fleet-wide
        assert finals[1] == finals[0] == finals[2]
        assert cbs[1].divergence_active is False    # repaired + cleared
        assert mons[1].healthy
        # the repair is durable in the newest manifest
        _, _, man = CheckpointManager(str(tmp_path / "ck1")).restore()
        repairs = man["extra"]["repairs"]
        assert len(repairs) == 1
        assert repairs[0]["reason"] == "param_divergence"
        assert repairs[0]["restored_global_step"] == 4
        assert repairs[0]["rewind"] is True
        # no data was skipped — rewind repairs REPLAY, not drop
        assert "skipped_windows" not in man["extra"]

    def test_poisoned_newer_checkpoints_are_discarded(self, tmp_path):
        """Saves taken between corruption and detection verify clean
        (CRC-wise) but hold poisoned numbers — the repair must remove
        them so a crash mid-replay can't resume from one."""
        tracker = {}

        class _SpyMgr(CheckpointManager):
            def discard_after(self, step):
                tracker["steps_at_discard"] = self.steps()
                removed = super().discard_after(step)
                tracker["removed"] = removed
                return removed

        store = TCPStore(is_master=True, world_size=1)

        def rank(r, faults=None):
            reg = MetricsRegistry()
            mon = HealthMonitor(action="rollback", registry=reg,
                                tracer=Tracer())
            cb = IntegrityCallback(store=store, rank=r, world_size=2,
                                   fingerprint_every=2, history=1000,
                                   monitor=mon, registry=reg,
                                   tracer=Tracer())
            ck = CheckpointCallback(
                manager=_SpyMgr(str(tmp_path / f"ck{r}")),
                every_n_steps=1)
            model = _model()
            if faults:
                with injected_faults(faults):
                    _fit(model, [cb, ck, mon])
            else:
                _fit(model, [cb, ck, mon])
            return ck

        rank(0)
        rank(1, FaultSpec("hapi.step_params", "bitflip",
                          occurrence=5, leaf="0.weight", bit=17))
        # at discard time the poisoned step-5/6 saves existed (intact
        # CRC-wise — they'd win any restore walk)...
        assert tracker["steps_at_discard"][-2:] == [5, 6]
        # ...and the repair removed exactly them, keeping 4
        assert tracker["removed"] == [5, 6]


# ------------------------------------------------------- audit-on-save


_TREE = {"w": np.arange(4096, dtype=np.float32),
         "b": np.ones(8, np.float32)}


@pytest.mark.faultinject
class TestAuditOnSave:
    def test_bitflip_after_commit_fails_audit_old_kept(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=1)
        mgr.save(_TREE, step=1)
        with injected_faults(FaultSpec("checkpoint.after_commit",
                                       "bitflip"), seed=0):
            with pytest.raises(CheckpointAuditError) as ei:
                mgr.save(_TREE, step=2, verify=True)
        assert ei.value.step == 2
        # retention GC did NOT run: the good step-1 save survives and
        # restore falls back to it
        assert mgr.steps() == [1, 2]
        step, tree, _ = CheckpointManager(str(tmp_path)).restore()
        assert step == 1
        np.testing.assert_array_equal(tree["w"], _TREE["w"])

    def test_without_verify_corrupt_save_becomes_only_candidate(
            self, tmp_path):
        """The hazard the audit closes: same fault, verify off — the
        corrupted save completes, GC removes the good one, and nothing
        restorable remains."""
        mgr = CheckpointManager(str(tmp_path), keep_last_n=1)
        mgr.save(_TREE, step=1)
        with injected_faults(FaultSpec("checkpoint.after_commit",
                                       "bitflip"), seed=0):
            mgr.save(_TREE, step=2)              # silent
        assert mgr.steps() == [2]
        with pytest.raises(FileNotFoundError):
            CheckpointManager(str(tmp_path)).restore()

    def test_torn_write_after_commit_old_kept(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=1)
        mgr.save(_TREE, step=1)
        with injected_faults(FaultSpec("checkpoint.after_commit",
                                       "torn_write"), seed=1):
            with pytest.raises(SimulatedCrash):
                mgr.save(_TREE, step=2, verify=True)
        step, _, _ = CheckpointManager(str(tmp_path)).restore()
        assert step == 1

    def test_async_audit_failure_surfaces_from_wait(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=1,
                                async_save=True, verify_on_save=True)
        mgr.save(_TREE, step=1)
        mgr.wait()
        with injected_faults(FaultSpec("checkpoint.after_commit",
                                       "bitflip"), seed=0):
            mgr.save(_TREE, step=2)
            with pytest.raises(CheckpointAuditError):
                mgr.wait()
        assert mgr.steps() == [1, 2]

    def test_clean_save_passes_audit(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=1,
                                verify_on_save=True)
        mgr.save(_TREE, step=1)
        mgr.save(_TREE, step=2)
        assert mgr.steps() == [2]                # GC ran normally

    def test_discard_after(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        for s in range(1, 6):
            mgr.save(_TREE, step=s)
        assert mgr.discard_after(2) == [3, 4, 5]
        assert mgr.steps() == [1, 2] and mgr.latest() == 2


# -------------------------------------------------- exporter endpoints


class TestIntegrityEndpoints:
    def test_integrity_endpoint_serves_report(self):
        reg = MetricsRegistry()
        cb = IntegrityCallback(rank=3, world_size=8,
                               fingerprint_every=25, registry=reg,
                               tracer=Tracer())
        srv = start_telemetry_server(port=0, registry=reg,
                                     tracer=Tracer(), integrity=cb)
        try:
            code, body = _get(srv.url + "/integrity")
            assert code == 200
            rep = json.loads(body)
            assert rep["rank"] == 3 and rep["world_size"] == 8
            assert rep["divergence_active"] is False
        finally:
            srv.stop()

    def test_integrity_404_without_sentinel(self):
        srv = start_telemetry_server(port=0, registry=MetricsRegistry(),
                                     tracer=Tracer())
        try:
            code, _ = _get(srv.url + "/integrity")
            assert code == 404
        finally:
            srv.stop()

    def test_healthz_folds_divergence_both_states(self):
        reg = MetricsRegistry()
        cb = IntegrityCallback(registry=reg, tracer=Tracer())
        srv = start_telemetry_server(port=0, registry=reg,
                                     tracer=Tracer(), integrity=cb)
        try:
            code, body = _get(srv.url + "/healthz")
            assert code == 200
            assert json.loads(body)["integrity_divergence_active"] is \
                False
            cb.divergence_active = True
            code, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 503
            assert health["healthy"] is False
            assert health["integrity_divergence_active"] is True
            cb.divergence_active = False         # repair reconverged
            code, _ = _get(srv.url + "/healthz")
            assert code == 200
        finally:
            srv.stop()

    def test_healthz_gauge_fallback_without_callback(self):
        """A multiprocess deployment folds the gauge instead of the
        in-process object."""
        reg = MetricsRegistry()
        reg.gauge("integrity_divergence_active", "t").set(1)
        srv = start_telemetry_server(port=0, registry=reg,
                                     tracer=Tracer())
        try:
            code, body = _get(srv.url + "/healthz")
            assert code == 503
            assert json.loads(body)["integrity_divergence_active"] is \
                True
        finally:
            srv.stop()


# -------------------------------------------- supervisor relaunch evidence


class TestSupervisorEvidence:
    def test_resume_evidence_carries_repairs_and_windows(self, tmp_path):
        from paddle_tpu.resilience import TrainingSupervisor

        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_TREE, step=7, extra={
            "global_step": 7,
            "repairs": [{"reason": "param_divergence",
                         "restored_global_step": 4}],
            "skipped_windows": [{"reason": "non_finite_loss",
                                 "first_step": 2, "last_step": 2}],
        })
        sup = TrainingSupervisor(cmd=["true"],
                                 checkpoint_dir=str(tmp_path))
        ev = sup._resume_evidence()
        assert ev["resume_step"] == 7
        assert ev["integrity_repairs"] == 1
        assert ev["last_repair_reason"] == "param_divergence"
        assert ev["skipped_windows"] == 1
        assert ev["last_rollback_reason"] == "non_finite_loss"

    def test_resume_evidence_plain_checkpoint(self, tmp_path):
        from paddle_tpu.resilience import TrainingSupervisor

        CheckpointManager(str(tmp_path)).save(_TREE, step=3)
        sup = TrainingSupervisor(cmd=["true"],
                                 checkpoint_dir=str(tmp_path))
        assert sup._resume_evidence() == {"resume_step": 3}


# -------------------------------------------------- silent-excepts lint


class TestExceptsLint:
    # the repo-wide sweep now runs ONCE in the consolidated suite:
    # tests/test_static_analysis.py::TestTier1Suite

    def test_lint_catches_planted_violations(self, tmp_path):
        mod = _load_tool("check_excepts")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            "import logging\n"
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        pass\n"                       # naked swallow
            "    try:\n"
            "        work()\n"
            "    except:\n"                        # bare except
            "        ...\n"
            "    for _ in y:\n"
            "        try:\n"
            "            work()\n"
            "        except (ValueError, Exception):\n"
            "            continue\n"               # broad via tuple
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        pass    # silent-ok:\n")      # marker w/o a reason
        out = mod.check(root=str(pkg))
        assert len(out) == 4
        assert all("mod.py" in o for o in out)

    def test_allowed_forms_pass(self, tmp_path):
        mod = _load_tool("check_excepts")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "ok.py").write_text(
            "import logging\n"
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        pass    # silent-ok: cleanup may race shutdown\n"
            "    try:\n"
            "        work()\n"
            "    except KeyError:\n"               # narrow: fine
            "        pass\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        logging.exception('boom')\n"  # logs: fine
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        raise\n")                     # re-raises: fine
        assert mod.check(root=str(pkg)) == []


# ------------------------------------------------------ overhead smoke


class TestSentinelOverheadSmoke:
    def test_amortized_overhead_under_bound(self):
        """Acceptance: fingerprint + replay cost, amortized over their
        default sampling intervals, stays under the documented 3% of
        step time at the bench config."""
        spec = importlib.util.spec_from_file_location(
            "bench_mod", os.path.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        out = bench.bench_integrity(steps=10, fp_reps=5, replay_reps=3)
        assert out["amortized_overhead_ratio"] < out["bound_ratio"], out
        # fingerprints must stay cheap in absolute terms too: digesting
        # ~8MB of params is milliseconds, not a second
        assert out["fingerprint_seconds_p50"] < 0.2, out
