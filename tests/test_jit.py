"""jit trace/save/load tests — the deployment tail (SURVEY L9).

The load-without-class test runs the predictor in a SUBPROCESS that never
imports the model class, proving the saved program is self-contained (the
AnalysisPredictor property the round-2 verdict flagged as missing).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _net():
    paddle.seed(21)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                         nn.Softmax())


class TestTraceToStatic:
    def test_to_static_matches_eager(self):
        net = _net()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        eager = net(x)
        traced = paddle.jit.to_static(net)
        static = traced(x)
        np.testing.assert_allclose(np.asarray(static.data),
                                   np.asarray(eager.data), atol=1e-6)

    def test_function_tracing(self):
        @paddle.jit.to_static
        def f(a, b):
            return a * 2 + b

        out = f(paddle.to_tensor(np.ones(3, np.float32)),
                paddle.to_tensor(np.ones(3, np.float32)))
        np.testing.assert_allclose(np.asarray(out.data), [3, 3, 3])


class TestSaveLoad:
    def test_load_into_layer(self, tmp_path):
        net = _net()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        ref = np.asarray(net(x).data)
        paddle.jit.save(net, str(tmp_path / "m"))

        fresh = _net()
        for p in fresh.parameters():   # scramble
            p.data = p.data * 0.0
        traced = paddle.jit.load(str(tmp_path / "m"), layer=fresh)
        np.testing.assert_allclose(np.asarray(traced(x).data), ref,
                                   atol=1e-6)

    def test_predictor_without_class(self, tmp_path):
        """jit.load(path) alone must EXECUTE the saved program."""
        net = _net()
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        ref = np.asarray(net(paddle.to_tensor(x)).data)
        paddle.jit.save(net, str(tmp_path / "m"),
                        example_inputs=[paddle.to_tensor(x)])
        assert os.path.exists(tmp_path / "m.pdmodel")
        assert os.path.exists(tmp_path / "m.stablehlo")

        pred = paddle.jit.load(str(tmp_path / "m"))
        out = pred(x)
        np.testing.assert_allclose(np.asarray(out.data), ref, atol=1e-6)

    def test_predictor_in_fresh_process(self, tmp_path):
        """Serving scenario: a process that never defines the model class
        loads the artifact and serves it."""
        net = _net()
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        ref = np.asarray(net(paddle.to_tensor(x)).data)
        paddle.jit.save(net, str(tmp_path / "m"),
                        example_inputs=[paddle.to_tensor(x)])
        np.save(tmp_path / "x.npy", x)
        np.save(tmp_path / "ref.npy", ref)

        script = f"""
import sys
sys.path.insert(0, {REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle

pred = paddle.jit.load({str(tmp_path / 'm')!r})
x = np.load({str(tmp_path / 'x.npy')!r})
out = pred(x)
np.testing.assert_allclose(np.asarray(out.data),
                           np.load({str(tmp_path / 'ref.npy')!r}), atol=1e-6)
print("PREDICTOR_OK")
"""
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("XLA_", "JAX_"))}
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "PREDICTOR_OK" in proc.stdout

    def test_predictor_without_program_raises(self, tmp_path):
        net = _net()
        paddle.jit.save(net, str(tmp_path / "m"))   # no example_inputs
        with pytest.raises(ValueError, match="example_inputs"):
            paddle.jit.load(str(tmp_path / "m"))
