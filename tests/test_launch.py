"""Launcher + multi-process env tests (reference strategy: multi-node is
simulated by multi-process on localhost — test_dist_base.py, SURVEY §4.3).

These spawn REAL worker processes on CPU devices with gloo collectives, so
the jax.distributed init path, the env contract, and the eager DP
allreduce stop being dead code.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy parity matrix (VERDICT r3 item 9)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER_OK = """
import os, sys
sys.path.insert(0, {repo!r})
import paddle_tpu.distributed as dist

env = dist.init_parallel_env()
assert env.world_size == 2, env.world_size
assert env.local_rank == int(os.environ["PADDLE_LOCAL_RANK"])
assert env.rank == int(os.environ["PADDLE_TRAINER_ID"])
assert len(env.trainer_endpoints) == 2

import jax, jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()), ("dp",))
out = jax.jit(jax.shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
                            in_specs=P("dp"), out_specs=P()))(
    jnp.arange(4.0))
np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])
print(f"WORKER_OK rank={{env.rank}} psum={{np.asarray(out).tolist()}}")
"""

WORKER_EAGER_DP = """
import os, sys
sys.path.insert(0, {repo!r})
import paddle_tpu.distributed as dist

env = dist.init_parallel_env()
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn

paddle.seed(7)   # same init on every rank
model = nn.Linear(4, 2)
dp = dist.DataParallel(model)
rank = env.rank
x = paddle.to_tensor(
    np.full((2, 4), float(rank + 1), dtype=np.float32))
loss = (dp(x) ** 2).mean()
loss.backward()
g_local = np.asarray(model.weight.grad.data).copy()
dp.apply_collective_grads()
g_sync = np.asarray(model.weight.grad.data)
# synced grad must differ from the local one and equal the cross-rank mean
assert not np.allclose(g_sync, g_local), "allreduce was a no-op"
print(f"WORKER_DP rank={{rank}} glocal={{float(g_local.sum()):.6f}} "
      f"gsum={{float(g_sync.sum()):.6f}}")
"""

WORKER_FAIL = """
import os, sys, time
rank = int(os.environ["PADDLE_TRAINER_ID"])
if rank == 1:
    sys.exit(3)
time.sleep(120)   # rank 0 hangs; the launcher must terminate it
"""


def _run_launch(tmp_path, worker_src, nproc=2, timeout=180,
                extra_args=()):
    script = tmp_path / "worker.py"
    script.write_text(worker_src.format(repo=REPO, tmp=str(tmp_path)))
    log_dir = tmp_path / "logs"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "XLA_", "JAX_"))}
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", str(nproc), "--backend", "gloo",
         "--log_dir", str(log_dir), *extra_args, str(script)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)
    logs = {}
    if log_dir.exists():
        for f in sorted(log_dir.iterdir()):
            logs[f.name] = f.read_text()
    return proc, logs


class TestLauncher:
    def test_two_process_collective(self, tmp_path):
        proc, logs = _run_launch(tmp_path, WORKER_OK)
        assert proc.returncode == 0, (proc.stdout, proc.stderr, logs)
        assert set(logs) == {"workerlog.0", "workerlog.1"}
        for rank in (0, 1):
            assert f"WORKER_OK rank={rank}" in logs[f"workerlog.{rank}"]
            assert "psum=[2.0, 4.0]" in logs[f"workerlog.{rank}"]

    def test_two_process_dcn_hybrid_mesh(self, tmp_path):
        """VERDICT r3 item 6: a jax.distributed-initialized 2-process run
        builds build_hybrid_mesh(dcn=dict(dp=2)) — 4 local devices per
        process, dp crossing the process (DCN) boundary — and allreduces
        across the full mesh."""
        proc, logs = _run_launch(tmp_path, WORKER_DCN)
        assert proc.returncode == 0, (proc.stdout, proc.stderr, logs)
        for rank in (0, 1):
            assert f"WORKER_DCN rank={rank} allreduce=28.0" in                 logs[f"workerlog.{rank}"]

    def test_eager_data_parallel(self, tmp_path):
        """VERDICT r2 #10: the eager DataParallel allreduce must really
        synchronize grads across worker processes."""
        proc, logs = _run_launch(tmp_path, WORKER_EAGER_DP)
        assert proc.returncode == 0, (proc.stdout, proc.stderr, logs)
        locals_, sums = [], []
        for rank in (0, 1):
            line = [l for l in logs[f"workerlog.{rank}"].splitlines()
                    if l.startswith("WORKER_DP")][0]
            locals_.append(float(line.split("glocal=")[1].split()[0]))
            sums.append(float(line.split("gsum=")[1]))
        # both ranks hold the identical grad, and it is the MEAN of the
        # two local grads (sum-without-divide would be 2x off)
        assert abs(sums[0] - sums[1]) < 1e-6
        expected = (locals_[0] + locals_[1]) / 2
        assert abs(sums[0] - expected) < 1e-5, (sums, locals_)

    def test_kill_worker_relaunch_recovers(self, tmp_path):
        """VERDICT r3 weak #8: a REAL kill-a-worker-and-relaunch
        integration — rank 1 SIGKILLs itself on the first attempt, the
        launcher tears the pod down and relaunches (--max_restarts), and
        the second attempt completes the collective on both ranks."""
        proc, logs = _run_launch(tmp_path, WORKER_ELASTIC,
                                 extra_args=("--max_restarts", "1"))
        assert proc.returncode == 0, (proc.returncode, proc.stderr, logs)
        assert (tmp_path / "crashed_once").exists()
        for rank in (0, 1):
            assert f"WORKER_ELASTIC rank={rank} attempt_survived" in \
                logs[f"workerlog.{rank}"], logs

    def test_failure_propagates_and_terminates(self, tmp_path):
        proc, logs = _run_launch(tmp_path, WORKER_FAIL, timeout=90)
        assert proc.returncode == 3, (proc.returncode, proc.stdout)


WORKER_DCN = """
import os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
sys.path.insert(0, {repo!r})
import paddle_tpu.distributed as dist

env = dist.init_parallel_env()
import jax, numpy as np, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from paddle_tpu.distributed.topology import build_hybrid_mesh

assert jax.process_count() == 2 and len(jax.devices()) == 8

# 2 slices of 4 local devices: mp/sharding inside a slice (ICI),
# dp across slices (DCN) — the ProcessGroupHeter two-tier pattern
mesh = build_hybrid_mesh(ici=dict(mp=2, sharding=2), dcn=dict(dp=2))
assert dict(mesh.shape)["dp"] == 2 and dict(mesh.shape)["mp"] == 2

grid = mesh.devices
pi = np.vectorize(lambda d: d.process_index)(grid)
# each dp slice lives entirely inside one process (ICI axes local)...
assert len(set(pi[0].ravel())) == 1 and len(set(pi[1].ravel())) == 1
# ...and the dp axis crosses the process (DCN) boundary
assert pi[0].ravel()[0] != pi[1].ravel()[0]

def f(_):
    i = (jax.lax.axis_index("dp") * 4 + jax.lax.axis_index("sharding") * 2
         + jax.lax.axis_index("mp"))
    return jax.lax.psum(i.astype(jnp.float32), ("dp", "sharding", "mp"))

out = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(), out_specs=P()))(
    jnp.zeros(()))
np.testing.assert_allclose(np.asarray(out), 28.0)   # sum 0..7 over DCN+ICI
print(f"WORKER_DCN rank={{env.rank}} allreduce={{float(np.asarray(out))}}")
"""


WORKER_ELASTIC = """
import os, signal, sys
sys.path.insert(0, {repo!r})
rank = int(os.environ["PADDLE_TRAINER_ID"])
marker = os.path.join({tmp!r}, "crashed_once")
if rank == 1 and not os.path.exists(marker):
    open(marker, "w").write("x")
    os.kill(os.getpid(), signal.SIGKILL)   # simulated node crash

import paddle_tpu.distributed as dist
env = dist.init_parallel_env()
import jax, jax.numpy as jnp, numpy as np
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()), ("dp",))
out = jax.jit(jax.shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
                            in_specs=P("dp"), out_specs=P()))(
    jnp.arange(4.0))
print(f"WORKER_ELASTIC rank={{env.rank}} attempt_survived "
      f"psum={{float(np.asarray(out).sum())}}")
"""
