"""Real GSPMD multi-chip execution over ``distributed.mesh``.

The promotion of multi-chip from the 8-way dry-run to REAL sharded
execution: every test here runs actual jitted programs on the 8 virtual
host devices the conftest forces, and placement is asserted against
``addressable_shards`` — what the devices actually hold, not what a
spec requested.  Covers: mesh construction/validation, the GPT
PartitionSpec rule table with per-leaf divisibility pruning, ZeRO
optimizer-state sharding, the dp=2 x mp=4 hapi train-step loss parity
vs single device, sharded eval, and mp-sharded serving greedy decode
token parity with the page pool living sharded end-to-end.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_init

CFG = GPTConfig(vocab_size=512, max_seq_len=64, hidden=64, num_layers=2,
                num_heads=4, ffn_hidden=256, dtype="float32",
                use_flash=False, remat="nothing")


def _ce_loss(out, y):
    from paddle_tpu.core.tensor import Tensor

    logits = (out.data if isinstance(out, Tensor) else out)
    logits = logits.astype(jnp.float32)
    yv = y.data if isinstance(y, Tensor) else y
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, yv[..., None], axis=-1)[..., 0]
    return -picked.mean()


# ---------------------------------------------------------- construction


class TestBuildMesh:
    def test_axes_and_order(self):
        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        assert mesh.axis_names == mesh_mod.AXIS_ORDER
        assert mesh_mod.axis_sizes(mesh) == {
            "dp": 2, "mp": 4, "pp": 1, "sharding": 1}
        assert mesh_mod.mesh_axis(mesh, "mp") == 4
        assert mesh_mod.mesh_axis(mesh, "nope") == 1

    def test_validates_device_count(self):
        with pytest.raises(ValueError, match="needs 16 devices"):
            mesh_mod.build_mesh(dp=4, mp=4)
        with pytest.raises(ValueError, match="must be >= 1"):
            mesh_mod.build_mesh(dp=0)

    def test_subset_of_devices(self):
        mesh = mesh_mod.build_mesh(mp=4)
        assert mesh.devices.size == 4

    def test_default_mesh_roundtrip(self):
        assert mesh_mod.default_mesh() is None
        m = mesh_mod.build_mesh(dp=2)
        try:
            assert mesh_mod.set_default_mesh(m) is m
            assert mesh_mod.default_mesh() is m
        finally:
            mesh_mod.set_default_mesh(None)


# ------------------------------------------------------------ rule table


class TestRuleTable:
    def test_gpt_specs(self):
        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        specs = mesh_mod.param_specs(gpt_init(CFG), mesh)
        assert specs["wte"] == P("mp", None)
        assert specs["blocks"]["qkv_w"] == P(None, None, "mp")
        assert specs["blocks"]["proj_w"] == P(None, "mp", None)
        assert specs["blocks"]["up_w"] == P(None, None, "mp")
        assert specs["blocks"]["down_w"] == P(None, "mp", None)
        # norms replicated
        assert specs["blocks"]["ln1_g"] == P(None, None)
        assert specs["lnf_g"] == P(None)

    def test_flat_names_hit_same_rules(self):
        """hapi flattens blocks/qkv_w -> blocks_qkv_w; same rule."""
        mesh = mesh_mod.build_mesh(mp=4)
        flat = {"blocks_qkv_w": np.zeros((2, 64, 192)),
                "wte": np.zeros((512, 64)),
                "blocks_ln1_g": np.zeros((2, 64))}
        specs = mesh_mod.param_specs(flat, mesh)
        assert specs["blocks_qkv_w"] == P(None, None, "mp")
        assert specs["wte"] == P("mp", None)
        assert specs["blocks_ln1_g"] == P(None, None)

    def test_indivisible_dim_degrades_to_replication(self):
        mesh = mesh_mod.build_mesh(mp=4)
        # 6 % 4 != 0: the mp split is pruned, not an error
        assert mesh_mod.resolve_spec(P(None, "mp"), (8, 6), mesh) == \
            P(None, None)
        assert mesh_mod.resolve_spec(P("mp"), (8,), mesh) == P("mp")

    def test_unknown_leaf_replicates(self):
        mesh = mesh_mod.build_mesh(mp=4)
        specs = mesh_mod.param_specs({"custom_thing": np.zeros((8, 8))},
                                     mesh)
        assert specs["custom_thing"] == P(None, None)

    def test_extra_rules_override(self):
        mesh = mesh_mod.build_mesh(mp=4)
        specs = mesh_mod.param_specs(
            {"custom_thing": np.zeros((8, 8))}, mesh,
            extra_rules=((r"custom_thing$", P(None, "mp")),))
        assert specs["custom_thing"] == P(None, "mp")


# ----------------------------------------------------- actual placement


class TestPlacement:
    def test_shard_params_addressable_shards(self):
        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        params = mesh_mod.shard_params(gpt_init(CFG), mesh)
        qkv = params["blocks"]["qkv_w"]
        # 8 local devices, 4 distinct windows (mp tiles), dp repeats them
        assert len(qkv.addressable_shards) == 8
        windows = {tuple((s.start, s.stop) for s in sh.index)
                   for sh in qkv.addressable_shards}
        assert len(windows) == 4
        assert qkv.addressable_shards[0].data.shape == (2, 64, 48)
        mesh_mod.assert_placement(qkv, mesh, P(None, None, "mp"),
                                  "qkv_w")
        mesh_mod.assert_placement(params["wte"], mesh, P("mp", None),
                                  "wte")
        mesh_mod.assert_placement(params["lnf_g"], mesh, P(), "lnf_g")

    def test_assert_placement_catches_wrong_layout(self):
        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        rep = jax.device_put(np.zeros((8, 8)), mesh_mod.replicated(mesh))
        with pytest.raises(AssertionError, match="shard shape"):
            mesh_mod.assert_placement(rep, mesh, P("mp", None), "w")

    def test_shard_batch(self):
        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        x, y = mesh_mod.shard_batch(mesh, np.zeros((8, 16)),
                                    np.zeros((8,), np.int32))
        assert x.sharding.spec == P("dp", None)
        assert y.sharding.spec == P("dp")
        # a batch the dp degree doesn't divide replicates, never dies
        z = mesh_mod.shard_batch(mesh, np.zeros((3, 4)))
        assert z.sharding.spec == P(None, None)

    def test_placement_report(self):
        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        params = mesh_mod.shard_params(gpt_init(CFG), mesh)
        rep = mesh_mod.placement_report(
            {"qkv_w": params["blocks"]["qkv_w"], "host": np.zeros(3)})
        assert rep["qkv_w"]["distinct_windows"] == 4
        assert rep["qkv_w"]["devices"] == 8
        assert rep["qkv_w"]["spec"] == [None, None, "mp"]
        assert rep["host"]["devices"] == 1


# ------------------------------------------------------------- ZeRO opt


class TestZeroOptSharding:
    def test_slots_pick_up_sharding_axis(self):
        mesh = mesh_mod.build_mesh(dp=2, mp=2, sharding=2)
        params = gpt_init(CFG)
        pspecs = mesh_mod.param_specs(params, mesh)
        slots = {k: {"moment1": np.zeros_like(v), "moment2":
                     np.zeros_like(v)}
                 for k, v in params["blocks"].items()}
        ospecs = mesh_mod.zero_opt_specs(pspecs["blocks"], slots, mesh)
        # qkv_w [L, D, 3D]: mp on dim 2, largest free dim (D=64) gets
        # the sharding split
        assert ospecs["qkv_w"]["moment1"] == P(None, "sharding", "mp")
        assert ospecs["qkv_w"]["moment2"] == P(None, "sharding", "mp")
        # replicated norm slots spread too (dim 1 = D divides)
        assert ospecs["ln1_g"]["moment1"] == P(None, "sharding")

    def test_no_sharding_axis_keeps_param_spec(self):
        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        specs = mesh_mod.zero_opt_specs(
            {"w": P(None, "mp")}, {"w": {"m": np.zeros((8, 8))}}, mesh)
        assert specs["w"]["m"] == P(None, "mp")

    def test_scalar_slots_replicate(self):
        mesh = mesh_mod.build_mesh(sharding=8)
        specs = mesh_mod.zero_opt_specs(
            {"w": P()}, {"w": {"count": np.zeros(())}}, mesh)
        assert specs["w"]["count"] == P()


# --------------------------------------------------------- replica peers


class TestReplicaPeers:
    def test_dp_groups_on_2x4(self):
        axes = {"dp": 2, "mp": 4}
        # rank = dp_idx * 4 + mp_idx; dp replicas share mp_idx
        assert mesh_mod.replica_peers(0, axes) == [0, 4]
        assert mesh_mod.replica_peers(5, axes) == [1, 5]
        assert mesh_mod.replica_peers(3, axes) == [3, 7]

    def test_three_axis_grid(self):
        axes = {"dp": 2, "mp": 2, "sharding": 2}
        assert mesh_mod.replica_peers(0, axes) == [0, 4]
        assert mesh_mod.replica_peers(7, axes) == [3, 7]
        assert mesh_mod.replica_peers(2, axes, axis="sharding") == [2, 3]

    def test_validates(self):
        with pytest.raises(ValueError, match="outside world"):
            mesh_mod.replica_peers(8, {"dp": 2, "mp": 4})
        with pytest.raises(ValueError, match="unknown mesh axis"):
            mesh_mod.replica_peers(0, {"dp": 2}, axis="bogus")


# ----------------------------------------------- hapi GSPMD train steps


def _fit_manual(mesh, n_steps=10, lr=1e-3):
    """n_steps of Model.train_batch on a tiny GPT under ``mesh``;
    returns (model, losses)."""
    import paddle_tpu
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.optimizer.optimizers import Adam

    paddle_tpu.seed(7)
    net = GPT(CFG)
    m = Model(net).prepare(optimizer=Adam(learning_rate=lr),
                           loss=_ce_loss, device_mesh=mesh)
    rng = np.random.RandomState(3)
    losses = []
    for _ in range(n_steps):
        x = rng.randint(0, CFG.vocab_size, (8, 16)).astype(np.int32)
        y = rng.randint(0, CFG.vocab_size, (8, 16)).astype(np.int32)
        loss, _ = m.train_batch([x], [y])
        losses.append(loss)
    return m, losses


class TestHapiGSPMD:
    def test_dp2_mp4_loss_parity_10_steps(self):
        """THE acceptance run: a real dp=2 x mp=4 GSPMD train step on 8
        host devices tracks the single-device loss curve for 10 steps
        within 1e-4 — and params / optimizer slots actually LIVE
        sharded between steps (addressable_shards, not dry-run specs)."""
        _, ref = _fit_manual(None)
        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        m, got = _fit_manual(mesh)
        np.testing.assert_allclose(got, ref, rtol=0, atol=1e-4)
        assert all(np.isfinite(got))
        named = dict(m.network.named_parameters())
        mesh_mod.assert_placement(named["blocks_qkv_w"].data, mesh,
                                  P(None, None, "mp"), "qkv_w")
        mesh_mod.assert_placement(named["wte"].data, mesh,
                                  P("mp", None), "wte")
        for slot in m._opt_state["slots"]["blocks_qkv_w"].values():
            mesh_mod.assert_placement(slot, mesh, P(None, None, "mp"),
                                      "qkv slot")

    def test_zero_sharded_opt_state_parity(self):
        """dp=2 x mp=2 x sharding=2: optimizer slots spread over the
        sharding axis (ZeRO) while the loss curve still matches."""
        _, ref = _fit_manual(None, n_steps=6)
        mesh = mesh_mod.build_mesh(dp=2, mp=2, sharding=2)
        m, got = _fit_manual(mesh, n_steps=6)
        np.testing.assert_allclose(got, ref, rtol=0, atol=1e-4)
        slot = m._opt_state["slots"]["blocks_qkv_w"]["moment1"]
        # param spec (None, None, mp) + sharding on the largest free dim
        mesh_mod.assert_placement(slot, mesh, P(None, "sharding", "mp"),
                                  "moment1")

    def test_sharded_eval_step(self):
        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        m, _ = _fit_manual(mesh, n_steps=2)
        m_ref, _ = _fit_manual(None, n_steps=2)
        rng = np.random.RandomState(11)
        x = rng.randint(0, CFG.vocab_size, (8, 16)).astype(np.int32)
        y = rng.randint(0, CFG.vocab_size, (8, 16)).astype(np.int32)
        loss, _ = m.eval_batch([x], [y])
        ref_loss, _ = m_ref.eval_batch([x], [y])
        assert abs(loss - ref_loss) < 1e-4

    def test_auto_mesh_is_pure_dp(self):
        from paddle_tpu.hapi.model import Model
        from paddle_tpu.optimizer.optimizers import Adam

        net = GPT(CFG)
        m = Model(net).prepare(optimizer=Adam(learning_rate=1e-3),
                               loss=_ce_loss, device_mesh="auto")
        assert mesh_mod.axis_sizes(m._mesh)["dp"] == len(jax.devices())
        rng = np.random.RandomState(0)
        x = rng.randint(0, CFG.vocab_size, (8, 16)).astype(np.int32)
        y = rng.randint(0, CFG.vocab_size, (8, 16)).astype(np.int32)
        loss, _ = m.train_batch([x], [y])
        assert np.isfinite(loss)
        # pure dp: params replicated on all 8 devices, batch split
        named = dict(m.network.named_parameters())
        mesh_mod.assert_placement(named["blocks_qkv_w"].data, m._mesh,
                                  P(), "qkv_w")


# ------------------------------------------------- mp-sharded serving


class TestServingGSPMD:
    def _prompts(self, n=4):
        return [list(np.random.RandomState(i).randint(
            1, CFG.vocab_size - 1, 6 + i)) for i in range(n)]

    def test_mp_sharded_greedy_token_identical(self):
        """Serving acceptance: the mp=4-sharded engine (params split
        per the rule table, KV page pool sharded on its head axis) is
        token-identical to the unsharded engine — and the pages are
        STILL sharded after generation (never gathered)."""
        from paddle_tpu.serving.engine import Engine, SamplingParams

        params = gpt_init(CFG, jax.random.key(0))
        sp = SamplingParams(max_new_tokens=8)
        prompts = self._prompts()
        ref = Engine(CFG, params, page_size=8, num_pages=64,
                     max_batch_size=4, chunk_len=16).generate(
                         prompts, sp)
        mesh = mesh_mod.build_mesh(mp=4)
        eng = Engine(CFG, params, page_size=8, num_pages=64,
                     max_batch_size=4, chunk_len=16, mesh=mesh)
        page_spec = P(None, None, None, "mp")
        mesh_mod.assert_placement(eng.cache.k_pages, mesh, page_spec,
                                  "k_pages")
        out = eng.generate(prompts, sp)
        assert out == ref
        mesh_mod.assert_placement(eng.cache.k_pages, mesh, page_spec,
                                  "k_pages after decode")
        mesh_mod.assert_placement(eng.cache.v_pages, mesh, page_spec,
                                  "v_pages after decode")
        mesh_mod.assert_placement(
            eng.params["blocks"]["qkv_w"], mesh, P(None, None, "mp"),
            "engine qkv_w")

    def test_dp_mp_mesh_pages_shard_on_mp_only(self):
        from paddle_tpu.serving.engine import Engine, SamplingParams

        mesh = mesh_mod.build_mesh(dp=2, mp=4)
        eng = Engine(CFG, gpt_init(CFG, jax.random.key(0)), page_size=8,
                     num_pages=64, max_batch_size=2, chunk_len=16,
                     mesh=mesh)
        mesh_mod.assert_placement(eng.cache.k_pages, mesh,
                                  P(None, None, None, "mp"), "k_pages")
        out = eng.generate(self._prompts(2),
                           SamplingParams(max_new_tokens=4))
        assert all(len(o) == 4 for o in out)

    def test_mesh_engine_preemption_keeps_parity(self):
        """Preemption-by-recompute under memory pressure must stay
        token-identical when the pool is mp-sharded."""
        from paddle_tpu.serving.engine import Engine, SamplingParams

        params = gpt_init(CFG, jax.random.key(1))
        sp = SamplingParams(max_new_tokens=6)
        prompts = self._prompts(3)
        kw = dict(page_size=4, num_pages=12, max_batch_size=3,
                  chunk_len=8)
        ref = Engine(CFG, params, **kw).generate(prompts, sp)
        mesh = mesh_mod.build_mesh(mp=4)
        assert Engine(CFG, params, mesh=mesh, **kw).generate(
            prompts, sp) == ref
