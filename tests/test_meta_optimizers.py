"""Comm-efficiency meta-optimizers (reference strategy: the dgc/localsgd
optimizer unit tests assert the rewritten program's semantics; here the
eager sync strategies are asserted numerically — residual conservation,
sparsity, sync cadence)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.hybrid_optimizer import \
    HybridParallelOptimizer
from paddle_tpu.distributed.fleet.meta_optimizers import (BF16AllreduceSync,
                                                          DGCSync, LocalSGD)
from paddle_tpu.distributed.fleet.distributed_strategy import \
    DistributedStrategy


def _model_with_grads(seed=0):
    paddle.seed(seed)
    m = nn.Linear(8, 8)
    x = paddle.to_tensor(
        np.random.RandomState(1).randn(4, 8).astype(np.float32))
    (m(x) ** 2).mean().backward()
    return m


class TestDGC:
    def test_topk_sparsity_and_residual_conservation(self):
        m = _model_with_grads()
        params = list(m.parameters())
        dense = {id(p): np.asarray(p.grad.data).copy() for p in params}

        sync = DGCSync(group=None, sparsity=0.1, momentum=0.0)
        sync.sync(params)
        for p in params:
            g = np.asarray(p.grad.data)
            nz = (g != 0).sum()
            k = int(np.ceil(g.size * 0.1))
            assert nz <= max(k, 1) + 1          # ties may widen by one
            # exchanged + residual == the full gradient (nothing lost)
            resid = np.asarray(sync._v[id(p)])
            np.testing.assert_allclose(g + resid, dense[id(p)],
                                       rtol=1e-6, atol=1e-7)

    def test_residual_drains_over_steps(self):
        """With a CONSTANT gradient, repeated syncs must eventually ship
        mass from every coordinate (the accumulate-then-send property)."""
        m = _model_with_grads()
        params = [p for p in m.parameters() if p.grad is not None]
        p = params[0]
        rng = np.random.RandomState(3)
        # comparable magnitudes (0.5..1.5): every coordinate's residual
        # grows at a similar rate, so accumulate-then-send must reach all
        const = (rng.uniform(0.5, 1.5, np.asarray(p.grad.data).shape)
                 * rng.choice([-1, 1], np.asarray(p.grad.data).shape)
                 ).astype(np.float32)

        sync = DGCSync(group=None, sparsity=0.05, momentum=0.0)
        shipped = np.zeros_like(const)
        for _ in range(60):
            p.grad.data = paddle.to_tensor(const).data
            sync.sync([p])
            shipped += np.asarray(p.grad.data)
        assert (np.abs(shipped) > 0).all()

    def test_rampup_syncs_dense(self):
        m = _model_with_grads()
        params = list(m.parameters())
        dense = {id(p): np.asarray(p.grad.data).copy() for p in params}
        sync = DGCSync(group=None, sparsity=0.01, rampup_begin_step=5)
        sync.sync(params)
        for p in params:     # step 1 <= rampup: untouched (world=1 mean)
            np.testing.assert_allclose(np.asarray(p.grad.data),
                                       dense[id(p)])


class TestBF16Allreduce:
    def test_wire_roundtrip_quantizes_to_bf16(self):
        m = _model_with_grads()
        params = list(m.parameters())
        dense = {id(p): np.asarray(p.grad.data).copy() for p in params}
        BF16AllreduceSync(group=None).sync(params)
        for p in params:
            g = np.asarray(p.grad.data)
            assert g.dtype == np.float32        # restored dtype
            bf = dense[id(p)].astype("bfloat16" if hasattr(np, "bfloat16")
                                     else np.float32)
            # value equals the bf16-rounded gradient, not the fp32 one
            import jax.numpy as jnp

            expect = np.asarray(jnp.asarray(dense[id(p)], jnp.bfloat16)
                                .astype(jnp.float32))
            np.testing.assert_allclose(g, expect)


class TestLocalSGD:
    def test_sync_cadence(self):
        m = _model_with_grads()
        params = list(m.parameters())
        ls = LocalSGD(group=None, k_steps=3)
        synced = [ls.after_step(params) for _ in range(7)]
        assert synced == [False, False, True, False, False, True, False]


class TestHybridParallelOptimizer:
    def _train(self, strategy, steps=3):
        paddle.seed(9)
        m = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        hopt = HybridParallelOptimizer(opt, hcg=None, strategy=strategy)
        rng = np.random.RandomState(2)
        x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        losses = []
        for _ in range(steps):
            loss = (m(x) ** 2).mean()
            loss.backward()
            hopt.step()
            hopt.clear_grad()
            losses.append(float(loss.data))
        return losses

    def test_default_and_metas_train(self):
        base = self._train(None)
        assert base[-1] < base[0]
        for knob in ("dgc", "localsgd", "fp16_allreduce"):
            s = DistributedStrategy()
            setattr(s, knob, True)
            losses = self._train(s)
            assert all(np.isfinite(losses)), knob
            assert losses[-1] < losses[0], knob

    def test_minimize_api(self):
        paddle.seed(3)
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        hopt = HybridParallelOptimizer(opt)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        w0 = np.asarray(m.weight.data).copy()
        hopt.minimize((m(x) ** 2).mean())
        assert not np.allclose(np.asarray(m.weight.data), w0)
        assert m.weight.grad is None    # cleared


class TestStrategyEngineMapping:
    def test_schedule_and_stages_map(self):
        """DistributedStrategy pipeline/sharding/gradient-merge fields
        drive the HybridEngine's EngineConfig (schedule_mode '1F1B' is a
        real schedule now, not a parity-surface string)."""
        from paddle_tpu.distributed.fleet import (
            DistributedStrategy, engine_config_from_strategy)

        s = DistributedStrategy()
        s.pipeline = True
        s.pipeline_configs.update(accumulate_steps=4,
                                  schedule_mode="F-then-B")
        s.sharding = True
        s.sharding_configs["stage"] = 3
        s.gradient_merge = True
        s.gradient_merge_configs["k_steps"] = 2
        ec = engine_config_from_strategy(s, lr=3e-4)
        assert ec.pipeline_schedule == "gpipe"
        assert ec.num_microbatches == 4
        assert ec.zero_stage == 3
        assert ec.accum_steps == 2
        assert ec.lr == 3e-4
        s.pipeline_configs["schedule_mode"] = "1F1B"
        assert engine_config_from_strategy(s).pipeline_schedule == "1f1b"
