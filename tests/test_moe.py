"""MoE / expert-parallel tests.

The reference's MoE test strategy (incubate moe_layer + gate tests,
hybrid_parallel parity runs) re-targeted at the TPU dense-dispatch design:
(a) gating semantics vs an independent NumPy reference,
(b) ep=N shard_map run matches the ep=1 run exactly,
(c) the engine's ep axis joins the hybrid parity matrix,
(d) gate facades (NaiveGate/SwitchGate/GShardGate).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.distributed.moe import (GShardGate, MoELayer, NaiveGate,
                                        SwitchGate, moe_capacity, moe_gating,
                                        moe_layer)

# ------------------------------------------------------------ NumPy oracle


def _np_softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def numpy_gating(logits, top_k, capacity, normalize=True):
    """Independent per-token re-implementation of GShard dense-dispatch
    gating (loops instead of cumsum/one-hot einsums)."""
    n, E = logits.shape
    C = capacity
    probs = _np_softmax(logits.astype(np.float64))
    combine = np.zeros((n, E, C))
    counts = np.zeros(E, np.int64)
    masked = probs.copy()
    rounds = []
    for _ in range(top_k):
        idx = masked.argmax(-1)
        gate = probs[np.arange(n), idx]
        pos = np.zeros(n, np.int64)
        for i in range(n):           # queue position within the expert,
            pos[i] = counts[idx[i]]  # continuing across routing rounds
            counts[idx[i]] += 1
        rounds.append((idx, gate, pos))
        masked[np.arange(n), idx] = 0.0
    # load balance on the top-1 assignment
    top1 = rounds[0][0]
    f = np.zeros(E)
    for e in range(E):
        f[e] = (top1 == e).mean()
    aux = E * float((f * probs.mean(0)).sum())

    denom = sum(g for _, g, _ in rounds) if (normalize and top_k > 1) else 1.0
    for idx, gate, pos in rounds:
        g = gate / denom if (normalize and top_k > 1) else gate
        for i in range(n):
            if pos[i] < C:
                combine[i, idx[i], pos[i]] += g[i]
    return combine, aux


class TestGating:
    def test_matches_numpy_top2(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(24, 4).astype(np.float32)
        C = moe_capacity(24, 4, 2.0, 2)
        combine, dispatch, aux = moe_gating(jnp.asarray(logits), top_k=2,
                                            capacity=C)
        ref_combine, ref_aux = numpy_gating(logits, 2, C)
        np.testing.assert_allclose(np.asarray(combine), ref_combine,
                                   atol=1e-5)
        np.testing.assert_allclose(float(aux), ref_aux, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(dispatch),
                                      ref_combine > 0)

    def test_matches_numpy_top1(self):
        rng = np.random.RandomState(1)
        logits = rng.randn(16, 8).astype(np.float32)
        C = moe_capacity(16, 8, 1.25, 1)
        combine, _, aux = moe_gating(jnp.asarray(logits), top_k=1, capacity=C)
        ref_combine, ref_aux = numpy_gating(logits, 1, C)
        np.testing.assert_allclose(np.asarray(combine), ref_combine,
                                   atol=1e-5)
        np.testing.assert_allclose(float(aux), ref_aux, atol=1e-5)

    def test_capacity_drop(self):
        """Tokens past an expert's capacity are dropped (combine weight 0),
        earlier tokens keep theirs — prune_gate_by_capacity semantics."""
        # all 6 tokens route top-1 to expert 0 (large logit margin)
        logits = np.full((6, 3), -10.0, np.float32)
        logits[:, 0] = 10.0
        logits[:, 1] = 0.0  # 2nd choice: expert 1
        combine, dispatch, _ = moe_gating(jnp.asarray(logits), top_k=1,
                                          capacity=2)
        c = np.asarray(combine)
        # exactly 2 tokens (the first two) hold expert-0 slots
        assert (c[:, 0].sum(-1) > 0).sum() == 2
        assert (c[:2, 0].sum(-1) > 0).all()
        assert (c[2:, 0] == 0).all()
        # every (expert, slot) holds at most one token
        assert (np.asarray(dispatch).sum(0) <= 1).all()

    def test_no_drop_at_high_capacity(self):
        rng = np.random.RandomState(2)
        logits = rng.randn(32, 4).astype(np.float32)
        combine, _, _ = moe_gating(jnp.asarray(logits), top_k=2, capacity=32)
        # with capacity >= n no token loses weight: rows sum to 1 (normalized)
        np.testing.assert_allclose(np.asarray(combine).sum((1, 2)),
                                   np.ones(32), atol=1e-5)


# --------------------------------------------------------------- moe_layer


def _moe_params(rng, E, D, F):
    return {
        "gate_w": rng.randn(D, E).astype(np.float32) * 0.5,
        "up_w": rng.randn(E, D, F).astype(np.float32) * 0.1,
        "up_b": rng.randn(E, F).astype(np.float32) * 0.1,
        "down_w": rng.randn(E, F, D).astype(np.float32) * 0.1,
        "down_b": rng.randn(E, D).astype(np.float32) * 0.1,
    }


@pytest.mark.slow
class TestMoELayer:
    def test_matches_per_token_reference(self):
        """moe_layer output == per-token sum_e gate_e * FFN_e(x) when no
        token is dropped."""
        rng = np.random.RandomState(3)
        E, D, F = 4, 8, 16
        params = _moe_params(rng, E, D, F)
        x = rng.randn(2, 6, D).astype(np.float32)
        y, _ = moe_layer(params, jnp.asarray(x), top_k=2,
                         capacity_factor=float(E))  # capacity = n: no drops

        probs = _np_softmax(x.reshape(-1, D) @ params["gate_w"])
        n = probs.shape[0]
        expect = np.zeros((n, D))
        for i in range(n):
            top2 = np.argsort(probs[i])[::-1][:2]
            denom = probs[i][top2].sum()
            for e in top2:
                h = x.reshape(-1, D)[i] @ params["up_w"][e] + params["up_b"][e]
                h = np.asarray(jax.nn.gelu(jnp.asarray(h), approximate=True))
                o = h @ params["down_w"][e] + params["down_b"][e]
                expect[i] += (probs[i][e] / denom) * o
        np.testing.assert_allclose(np.asarray(y).reshape(n, D), expect,
                                   atol=1e-4)

    def test_ep4_matches_ep1(self):
        """Explicit expert parallelism over ep=4 returns the identical
        output: same gating, experts resharded, balanced all_to_all."""
        rng = np.random.RandomState(4)
        E, D, F = 8, 16, 32
        params = _moe_params(rng, E, D, F)
        x = rng.randn(8, 4, D).astype(np.float32)

        y1, aux1 = moe_layer(params, jnp.asarray(x), top_k=2,
                             capacity_factor=float(E))

        mesh = Mesh(np.array(jax.devices()[:4]), ("ep",))
        pspecs = {"gate_w": P(), "up_w": P("ep"), "up_b": P("ep"),
                  "down_w": P("ep"), "down_b": P("ep")}

        def run(p, xs):
            y, aux = moe_layer(p, xs, top_k=2, capacity_factor=float(E),
                               ep_axis="ep")
            return y, jax.lax.pmean(aux, "ep")

        mapped = jax.shard_map(run, mesh=mesh,
                               in_specs=(pspecs, P("ep", None, None)),
                               out_specs=(P("ep", None, None), P()))
        y4, aux4 = mapped(params, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(y4), np.asarray(y1), atol=1e-5)
        # aux: mean of per-shard values vs full-batch value — same stats
        # family, not identical; sanity-bound only
        assert abs(float(aux4) - float(aux1)) < 0.5

    def test_gate_facades(self):
        for gate_cls, top_k in ((NaiveGate, 2), (SwitchGate, 1),
                                (GShardGate, 2)):
            layer = MoELayer(d_model=8, d_hidden=16, num_experts=4,
                             gate=gate_cls(8, 4))
            assert layer.top_k == top_k
            import paddle_tpu

            x = paddle_tpu.to_tensor(
                np.random.RandomState(5).randn(2, 6, 8).astype(np.float32))
            y = layer(x)
            assert tuple(y.shape) == (2, 6, 8)
            assert layer.aux_loss is not None
            assert np.isfinite(float(layer.aux_loss.data
                                     if hasattr(layer.aux_loss, "data")
                                     else layer.aux_loss))

    def test_gate_by_name(self):
        layer = MoELayer(d_model=8, d_hidden=16, num_experts=4, gate="switch")
        assert isinstance(layer.gate, SwitchGate)
        assert layer.top_k == 1


# ------------------------------------------------------------ engine parity


from paddle_tpu.distributed.engine import EngineConfig, HybridEngine
from paddle_tpu.models.gpt import GPTConfig, gpt_loss

MOE_CFG = GPTConfig(vocab_size=256, max_seq_len=64, hidden=64, num_layers=4,
                    num_heads=4, ffn_hidden=128, dtype="float32",
                    use_flash=False, remat="nothing",
                    moe_experts=4, moe_top_k=2,
                    moe_capacity_factor=8.0)  # no drops: exact parity


def _batch(bs=8, seq=32, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, MOE_CFG.vocab_size, (bs, seq)).astype(np.int32)
    labels = np.concatenate([tokens[:, 1:], np.full((bs, 1), -100)],
                            axis=1).astype(np.int32)
    return tokens, labels


def _run_steps(engine, n=3, bs=8, seq=32):
    params, opt = engine.init(seed=0)
    losses = []
    tokens, labels = _batch(bs, seq)
    for _ in range(n):
        params, opt, loss = engine.step(params, opt, tokens, labels, lr=1e-3)
        losses.append(float(loss))
    return losses, engine.gather_params(params)


@pytest.fixture(scope="module")
def moe_baseline():
    eng = HybridEngine(MOE_CFG, devices=jax.devices()[:1])
    return _run_steps(eng)


@pytest.mark.slow
class TestEngineMoE:
    def test_single_device_loss_sane(self, moe_baseline):
        losses, _ = moe_baseline
        assert abs(losses[0] - np.log(MOE_CFG.vocab_size)) < 1.0
        assert losses[-1] < losses[0]

    def test_engine_loss_equals_gpt_loss(self):
        """Engine loss (incl. the aux term) == gpt_loss on the same params:
        the two loss paths must agree (VERDICT r2 missing #2)."""
        eng = HybridEngine(MOE_CFG, dp=2, ep=2, mp=2)
        params, opt = eng.init(seed=0)
        host = eng.gather_params(params)
        tokens, labels = _batch()
        _, _, loss = eng.step(params, opt, tokens, labels, lr=1e-3)
        ref = float(gpt_loss(MOE_CFG, host, tokens, labels))
        np.testing.assert_allclose(float(loss), ref, rtol=1e-4)

    # NOTE on tolerances: the FFN/CE math is exactly parallel (no token
    # drops at capacity_factor=8), but the aux loss is computed per data
    # shard / microbatch and averaged — mean_s(E·Σ f_s·p_s) is not the
    # full-batch E·Σ f·p (a product of means), exactly like the reference's
    # per-rank gate loss under DP.  With moe_aux_weight=0.01 this puts an
    # O(1e-3) floor on multi-step loss parity vs the single-device run.

    def test_ep2_matches(self, moe_baseline):
        eng = HybridEngine(MOE_CFG, ep=2, devices=jax.devices()[:2])
        losses, _ = _run_steps(eng)
        np.testing.assert_allclose(losses, moe_baseline[0], atol=2e-3)

    def test_ep2_dp2_mp2_matches(self, moe_baseline):
        eng = HybridEngine(MOE_CFG, dp=2, ep=2, mp=2)
        losses, _ = _run_steps(eng)
        np.testing.assert_allclose(losses, moe_baseline[0], atol=2e-3)

    def test_ep2_pp2_matches(self, moe_baseline):
        eng = HybridEngine(MOE_CFG, pp=2, ep=2, dp=2,
                           engine_cfg=EngineConfig(num_microbatches=2))
        losses, _ = _run_steps(eng)
        np.testing.assert_allclose(losses, moe_baseline[0], atol=2e-3)

    def test_params_stay_synced(self, moe_baseline):
        """Replicated param shards must be IDENTICAL across ranks after
        training (the TP/EP grad-sync invariant), and the whole tree must
        track the single-device run up to the aux-stat drift."""
        _, base_params = moe_baseline
        eng = HybridEngine(MOE_CFG, dp=2, ep=2, mp=2)
        params, opt = eng.init(seed=0)
        tokens, labels = _batch()
        for _ in range(3):
            params, opt, _ = eng.step(params, opt, tokens, labels, lr=1e-3)
        # exact cross-replica agreement: shards covering the same logical
        # slice must be bitwise equal on every device that holds them
        for leaf in jax.tree_util.tree_leaves(params):
            by_index = {}
            for shard in leaf.addressable_shards:
                key = str(shard.index)
                if key in by_index:
                    np.testing.assert_array_equal(
                        np.asarray(shard.data), by_index[key])
                else:
                    by_index[key] = np.asarray(shard.data)
        # and the values track the baseline (aux drift bounds this, see
        # tolerance NOTE above; gate_w is the most sensitive leaf)
        flat_a = jax.tree_util.tree_leaves(base_params)
        flat_b = jax.tree_util.tree_leaves(eng.gather_params(params))
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-3)
