"""Tensor-parallel layer tests (the reference's hybrid_parallel_mp_layers
strategy: every parallel layer must match its dense equivalent).

Covers BOTH modes: explicit shard_map collectives and GSPMD sharding
annotations.  Round-2 verdict weak #7: these layers were test-free and the
explicit mode was docstring-only.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mp_layers import (ColumnParallelLinear,
                                              ParallelCrossEntropy,
                                              RowParallelLinear,
                                              VocabParallelEmbedding,
                                              parallel_cross_entropy)

MP = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:MP]), ("mp",))


def _dense(rng, d_in, d_hidden, d_out):
    w1 = rng.randn(d_in, d_hidden).astype(np.float32) * 0.1
    b1 = rng.randn(d_hidden).astype(np.float32) * 0.1
    w2 = rng.randn(d_hidden, d_out).astype(np.float32) * 0.1
    b2 = rng.randn(d_out).astype(np.float32) * 0.1
    return w1, b1, w2, b2


class TestExplicitMode:
    """Pre-split weights inside shard_map: the reference's manual schedule."""

    def test_column_row_matches_dense(self):
        rng = np.random.RandomState(0)
        w1, b1, w2, b2 = _dense(rng, 8, 16, 8)
        x = rng.randn(4, 8).astype(np.float32)
        ref = (x @ w1 + b1) @ w2 + b2

        col = ColumnParallelLinear(8, 16, gather_output=False,
                                   num_partitions=MP)
        row = RowParallelLinear(16, 8, input_is_parallel=True,
                                num_partitions=MP)

        def local(w1_l, b1_l, w2_l, b2_f, xs):
            with col.swap_state({"weight": w1_l, "bias": b1_l}):
                with row.swap_state({"weight": w2_l, "bias": b2_f}):
                    h = col(Tensor(xs))
                    y = row(h)
            return y.data

        mapped = jax.shard_map(
            local, mesh=_mesh(),
            in_specs=(P(None, "mp"), P("mp"), P("mp", None), P(), P()),
            out_specs=P(), check_vma=True)
        out = jax.jit(mapped)(w1, b1, w2, b2, x)
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    def test_column_gather_output(self):
        rng = np.random.RandomState(1)
        w1 = rng.randn(8, 16).astype(np.float32) * 0.1
        x = rng.randn(4, 8).astype(np.float32)
        col = ColumnParallelLinear(8, 16, has_bias=False, gather_output=True,
                                   num_partitions=MP)

        def local(w_l, xs):
            with col.swap_state({"weight": w_l}):
                y = col(Tensor(xs))
            # gathered output is full-width and replicated over mp
            return jax.lax.pmax(y.data, "mp")

        mapped = jax.shard_map(local, mesh=_mesh(),
                               in_specs=(P(None, "mp"), P()),
                               out_specs=P(), check_vma=True)
        out = jax.jit(mapped)(w1, x)
        np.testing.assert_allclose(np.asarray(out), x @ w1, atol=1e-5)

    def test_row_splits_unparallel_input(self):
        rng = np.random.RandomState(2)
        w2 = rng.randn(16, 8).astype(np.float32) * 0.1
        x = rng.randn(4, 16).astype(np.float32)
        row = RowParallelLinear(16, 8, has_bias=False,
                                input_is_parallel=False, num_partitions=MP)

        def local(w_l, xs):
            with row.swap_state({"weight": w_l}):
                return row(Tensor(xs)).data

        mapped = jax.shard_map(local, mesh=_mesh(),
                               in_specs=(P("mp", None), P()),
                               out_specs=P(), check_vma=True)
        out = jax.jit(mapped)(w2, x)
        np.testing.assert_allclose(np.asarray(out), x @ w2, atol=1e-5)

    def test_grads_match_dense(self):
        """Column(no-gather) + Row: weight grads == dense autodiff grads."""
        rng = np.random.RandomState(3)
        w1, b1, w2, b2 = _dense(rng, 8, 16, 8)
        x = rng.randn(4, 8).astype(np.float32)

        def dense_loss(w1, b1, w2, b2):
            return ((jnp.asarray(x) @ w1 + b1) @ w2 + b2).sum()

        ref = jax.grad(dense_loss, argnums=(0, 1, 2, 3))(w1, b1, w2, b2)

        col = ColumnParallelLinear(8, 16, gather_output=False,
                                   num_partitions=MP)
        row = RowParallelLinear(16, 8, input_is_parallel=True,
                                num_partitions=MP)

        def local_loss(w1_l, b1_l, w2_l, b2_f):
            with col.swap_state({"weight": w1_l, "bias": b1_l}):
                with row.swap_state({"weight": w2_l, "bias": b2_f}):
                    y = row(col(Tensor(jnp.asarray(x))))
            s = y.data.sum()
            from paddle_tpu.core.vma import lift_to

            return jax.lax.psum(lift_to(s, ("mp",)), "mp") / MP

        grads = jax.jit(jax.shard_map(
            jax.grad(local_loss, argnums=(0, 1, 2, 3)), mesh=_mesh(),
            in_specs=(P(None, "mp"), P("mp"), P("mp", None), P()),
            out_specs=(P(None, "mp"), P("mp"), P("mp", None), P()),
            check_vma=True))(w1, b1, w2, b2)
        for g, r, name in zip(grads, ref, ("w1", "b1", "w2", "b2")):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       atol=1e-4, err_msg=name)


class TestGSPMDMode:
    """Weights carry PartitionSpecs; pjit/GSPMD inserts the collectives."""

    def test_column_row_matches_dense(self):
        rng = np.random.RandomState(4)
        w1, b1, w2, b2 = _dense(rng, 8, 16, 8)
        x = rng.randn(4, 8).astype(np.float32)
        ref = (x @ w1 + b1) @ w2 + b2

        mesh = _mesh()
        col = ColumnParallelLinear(8, 16, num_partitions=MP)
        row = RowParallelLinear(16, 8, num_partitions=MP)
        # place weights per sharding_specs
        col.weight.data = jax.device_put(
            w1, NamedSharding(mesh, col.sharding_specs()["weight"]))
        col.bias.data = jax.device_put(
            b1, NamedSharding(mesh, col.sharding_specs()["bias"]))
        row.weight.data = jax.device_put(
            w2, NamedSharding(mesh, row.sharding_specs()["weight"]))
        row.bias.data = jax.device_put(
            b2, NamedSharding(mesh, row.sharding_specs()["bias"]))

        def f(p_col, p_row, xs):
            with col.swap_state(p_col):
                with row.swap_state(p_row):
                    return row(col(Tensor(xs))).data

        out = jax.jit(f)({"weight": col.weight.data, "bias": col.bias.data},
                         {"weight": row.weight.data, "bias": row.bias.data},
                         jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    def test_vocab_parallel_embedding(self):
        rng = np.random.RandomState(5)
        table = rng.randn(32, 8).astype(np.float32)
        ids = rng.randint(0, 32, (4, 6))
        emb = VocabParallelEmbedding(32, 8)
        emb.weight.data = jax.device_put(
            table, NamedSharding(_mesh(), emb.sharding_specs()["weight"]))
        out = emb(Tensor(jnp.asarray(ids)))
        np.testing.assert_allclose(np.asarray(out.data), table[ids],
                                   atol=1e-6)


class TestParallelCrossEntropy:
    def test_matches_full_softmax(self):
        rng = np.random.RandomState(6)
        logits = rng.randn(4, 6, 32).astype(np.float32)
        labels = rng.randint(0, 32, (4, 6)).astype(np.int32)
        labels[0, 0] = -100   # ignore_index

        lf = jnp.asarray(logits)
        logp = jax.nn.log_softmax(lf, axis=-1)
        safe = jnp.maximum(jnp.asarray(labels), 0)
        ref = -jnp.take_along_axis(logp, safe[..., None], -1)[..., 0]
        ref = jnp.where(jnp.asarray(labels) == -100, 0.0, ref)

        mapped = jax.shard_map(
            lambda lg, lb: parallel_cross_entropy(lg, lb, mp_axis="mp"),
            mesh=_mesh(), in_specs=(P(None, None, "mp"), P()),
            out_specs=P(), check_vma=True)
        out = jax.jit(mapped)(lf, jnp.asarray(labels))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)
