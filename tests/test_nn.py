"""Layer zoo tests (parity: reference API/layer test style — dygraph vs numpy)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn


def test_linear_forward():
    layer = nn.Linear(4, 3)
    x = paddle.to_tensor(np.random.rand(2, 4).astype(np.float32))
    out = layer(x)
    expected = np.asarray(x.data) @ np.asarray(layer.weight.data) + \
        np.asarray(layer.bias.data)
    np.testing.assert_allclose(np.asarray(out.data), expected, atol=1e-5)


def test_linear_backward():
    layer = nn.Linear(4, 3)
    x = paddle.to_tensor(np.random.rand(2, 4).astype(np.float32))
    loss = layer(x).sum()
    loss.backward()
    assert layer.weight.grad is not None
    assert layer.bias.grad is not None
    np.testing.assert_allclose(np.asarray(layer.bias.grad.data), [2.0] * 3)


def test_sequential_and_state_dict():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = model.state_dict()
    assert set(sd) == {"0.weight", "0.bias", "2.weight", "2.bias"}
    model2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    missing, unexpected = model2.set_state_dict(sd)
    assert not missing and not unexpected
    x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
    np.testing.assert_allclose(np.asarray(model(x).data),
                               np.asarray(model2(x).data), atol=1e-6)


def test_named_parameters_nested():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(2, 2)
            self.block = nn.Sequential(nn.Linear(2, 2))

        def forward(self, x):
            return self.block(self.fc1(x))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert "fc1.weight" in names and "block.0.weight" in names
    assert len(net.parameters()) == 4


def test_dropout_modes():
    layer = nn.Dropout(0.5)
    x = paddle.to_tensor(np.ones((100, 100), np.float32))
    layer.eval()
    np.testing.assert_allclose(np.asarray(layer(x).data), np.ones((100, 100)))
    layer.train()
    out = np.asarray(layer(x).data)
    frac_zero = (out == 0).mean()
    assert 0.3 < frac_zero < 0.7
    # upscale keeps expectation
    assert abs(out.mean() - 1.0) < 0.1


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.to_tensor(np.random.rand(4, 3, 5, 5).astype(np.float32) * 3 + 1)
    bn.train()
    out = bn(x)
    o = np.asarray(out.data)
    np.testing.assert_allclose(o.mean(axis=(0, 2, 3)), 0.0, atol=1e-4)
    # running stats moved off init
    assert not np.allclose(np.asarray(bn._mean.data), 0.0)
    bn.eval()
    out2 = bn(x)
    assert out2.shape == [4, 3, 5, 5]


def test_layernorm_layer():
    ln = nn.LayerNorm(8)
    x = paddle.to_tensor(np.random.rand(2, 5, 8).astype(np.float32))
    out = np.asarray(ln(x).data)
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)


def test_embedding_layer():
    emb = nn.Embedding(10, 6)
    ids = paddle.to_tensor(np.array([[0, 1], [2, 3]]))
    assert emb(ids).shape == [2, 2, 6]


def test_conv_bn_relu_stack():
    model = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8), nn.ReLU(),
        nn.MaxPool2D(2, 2))
    x = paddle.to_tensor(np.random.rand(2, 3, 8, 8).astype(np.float32))
    assert model(x).shape == [2, 8, 4, 4]


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.to_tensor(np.random.rand(2, 5, 16).astype(np.float32))
    out = mha(x)
    assert out.shape == [2, 5, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.to_tensor(np.random.rand(2, 6, 16).astype(np.float32))
    assert enc(x).shape == [2, 6, 16]
    # layers are independent copies
    p0 = enc.layers[0].linear1.weight
    p1 = enc.layers[1].linear1.weight
    assert p0 is not p1


def test_lstm():
    lstm = nn.LSTM(4, 8, num_layers=1)
    x = paddle.to_tensor(np.random.rand(2, 5, 4).astype(np.float32))
    out, states = lstm(x)
    assert out.shape == [2, 5, 8]


def test_rms_norm():
    rn = nn.RMSNorm(8)
    x = paddle.to_tensor(np.random.rand(2, 8).astype(np.float32))
    out = np.asarray(rn(x).data)
    xn = np.asarray(x.data)
    expected = xn / np.sqrt((xn ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out, expected, atol=1e-5)


def test_train_eval_propagates():
    model = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    model.eval()
    assert not model[1].training
    model.train()
    assert model[1].training


def test_parameter_dtype_to():
    model = nn.Linear(4, 3)
    model.to(dtype="bfloat16")
    assert model.weight.data.dtype == paddle.bfloat16
