"""Unified observability layer tests: MetricsRegistry snapshot /
Prometheus exposition, thread-safety, the JIT compile watchdog (the
ragged-shape regression detector), the step-aware Profiler scheduler,
chrome-trace export with step instants + counter tracks, and the
Benchmark timer warmup-boundary regression."""
import json
import logging
import re
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.observability import (CompileWatchdog, Counter, Gauge,
                                      Histogram, MetricsRegistry,
                                      default_registry, default_watchdog,
                                      watchdog_enabled)
from paddle_tpu.profiler import (Profiler, ProfilerState, RecordEvent,
                                 make_scheduler)


@pytest.fixture(autouse=True)
def _clean_watchdog():
    wd = default_watchdog()
    prev = wd.enabled
    wd.reset()
    yield
    wd.enabled = prev
    wd.reset()


@pytest.fixture
def obs_caplog(caplog):
    """caplog wired to the observability logger: the framework's
    'paddle_tpu' parent logger sets propagate=False (per-rank handler),
    so records never reach caplog's root handler on their own."""
    log = logging.getLogger("paddle_tpu.observability")
    log.addHandler(caplog.handler)
    try:
        yield caplog
    finally:
        log.removeHandler(caplog.handler)


# ---------------------------------------------------------------- registry
class TestMetricsRegistry:
    def test_counter_gauge_histogram_snapshot(self):
        reg = MetricsRegistry()
        reg.counter("reqs").inc(3)
        g = reg.gauge("occ")
        g.set(0.8)
        g.set(0.5)
        h = reg.histogram("lat")
        for v in (0.01, 0.02, 0.04):
            h.observe(v)
        snap = reg.snapshot()
        assert snap["reqs"] == {"type": "counter", "value": 3}
        assert snap["occ"]["value"] == {"current": 0.5, "peak": 0.8}
        assert snap["lat"]["value"]["count"] == 3
        assert snap["lat"]["value"]["p50"] == 0.02
        json.dumps(snap)                     # JSON-able end to end

    def test_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("compiles", labelnames=("fn",))
        c.labels(fn="prefill").inc(2)
        c.labels(fn="decode").inc()
        c.labels(fn="prefill").inc()         # same child
        snap = reg.snapshot()["compiles"]
        series = {s["labels"]["fn"]: s["value"] for s in snap["series"]}
        assert series == {"prefill": 3, "decode": 1}
        with pytest.raises(ValueError):
            c.inc()                          # family needs .labels()
        with pytest.raises(ValueError):
            reg.gauge("compiles")            # kind mismatch

    def test_get_or_create_and_replace(self):
        reg = MetricsRegistry()
        a = reg.counter("x")
        assert reg.counter("x") is a
        a.inc(5)
        fresh = Counter("x")
        reg.register(fresh, replace=True)    # the reset idiom
        assert reg.snapshot()["x"]["value"] == 0
        with pytest.raises(ValueError):
            reg.register(Counter("x"))       # no silent replacement

    def test_prometheus_round_trip(self):
        """Every sample line in the exposition must be parseable and
        must agree with the snapshot."""
        reg = MetricsRegistry()
        reg.counter("reqs_total", labelnames=("code",)) \
            .labels(code=200).inc(7)
        reg.gauge("occ").set(0.25)
        h = reg.histogram("lat_s")
        for v in (0.0001, 0.01, 5.0):
            h.observe(v)
        text = reg.expose_prometheus()
        line = re.compile(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)$')
        samples = {}
        for ln in text.splitlines():
            if ln.startswith("#"):
                assert ln.startswith(("# HELP ", "# TYPE "))
                continue
            m = line.match(ln)
            assert m, f"unparseable exposition line: {ln!r}"
            samples[m.group(1) + (m.group(2) or "")] = float(m.group(3))
        assert samples['reqs_total{code="200"}'] == 7
        assert samples["occ"] == 0.25
        assert samples["lat_s_count"] == 3
        assert abs(samples["lat_s_sum"] - 5.0101) < 1e-9
        assert samples['lat_s_bucket{le="+Inf"}'] == 3
        # buckets are cumulative and monotone
        buckets = [(float(k.split('le="')[1].rstrip('"}')), v)
                   for k, v in samples.items()
                   if k.startswith("lat_s_bucket") and "+Inf" not in k]
        vals = [v for _, v in sorted(buckets)]
        assert vals == sorted(vals)
        assert vals[-1] <= 3

    def test_histogram_thread_safety(self):
        """observe() from worker threads while the main thread snapshots:
        the old list-mutation-during-sort race crashed here."""
        h = Histogram("lat")
        stop = threading.Event()
        errs = []

        def hammer():
            i = 0
            while not stop.is_set():
                h.observe(i % 100 * 1e-3)
                i += 1

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(200):
                s = h.summary()
                # before the first observe lands, stats are None-filled
                assert s["count"] == 0 or s["p99"] >= s["p50"]
                h.percentile(95)
        except Exception as e:              # pragma: no cover
            errs.append(e)
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errs

    def test_summary_sorts_reservoir_once(self, monkeypatch):
        import paddle_tpu.observability.metrics as om

        calls = {"n": 0}
        real_sorted = sorted

        def counting_sorted(*a, **k):
            calls["n"] += 1
            return real_sorted(*a, **k)

        # shadow the builtin in the module's global namespace
        monkeypatch.setattr(om, "sorted", counting_sorted, raising=False)
        h = Histogram("lat")
        for v in (3.0, 1.0, 2.0):
            h.observe(v)
        s = h.summary()
        assert calls["n"] == 1               # one sort for p50+p95+p99
        assert (s["p50"], s["p95"], s["p99"]) == (2.0, 3.0, 3.0)


# ---------------------------------------------------------------- watchdog
class TestCompileWatchdog:
    def _watched_step(self, wd):
        def step(x, y):
            return (x * y).sum()

        return wd.watch(jax.jit(step), name="test::step")

    def test_recompile_flagged_once_with_shape_diff(self, obs_caplog):
        """The acceptance scenario: same-shape calls log nothing; ONE
        changed-shape call logs exactly one WARNING carrying the
        per-argument shape diff."""
        wd = CompileWatchdog(registry=MetricsRegistry())
        wd.enable()
        f = self._watched_step(wd)
        x4 = jnp.ones((4, 2))
        with obs_caplog.at_level(logging.WARNING,
                             logger="paddle_tpu.observability"):
            for _ in range(3):
                f(x4, x4)                    # warmup + cache hits
            assert obs_caplog.records == []
            f(jnp.ones((8, 2)), jnp.ones((8, 2)))   # ragged batch
        warnings = [r for r in obs_caplog.records
                    if r.levelno == logging.WARNING]
        assert len(warnings) == 1
        msg = warnings[0].getMessage()
        assert "test::step" in msg
        assert "f32[4,2] -> f32[8,2]" in msg
        rep = wd.report()["test::step"]
        assert rep["calls"] == 4
        assert rep["compiles"] == 2
        assert rep["recompiles"] == 1
        assert rep["compile_time_s"] > 0

    def test_silent_when_disabled_and_counters_in_registry(self, obs_caplog):
        reg = MetricsRegistry()
        wd = CompileWatchdog(registry=reg)
        f = self._watched_step(wd)           # disabled: pure pass-through
        with obs_caplog.at_level(logging.WARNING,
                             logger="paddle_tpu.observability"):
            f(jnp.ones((2, 2)), jnp.ones((2, 2)))
            f(jnp.ones((5, 2)), jnp.ones((5, 2)))
        assert obs_caplog.records == []
        assert wd.report() == {}

        wd.enable()
        f(jnp.ones((3, 2)), jnp.ones((3, 2)))
        f(jnp.ones((6, 2)), jnp.ones((6, 2)))
        snap = reg.snapshot()
        series = {s["labels"]["fn"]: s["value"]
                  for s in snap["jit_compiles_total"]["series"]}
        assert series["test::step"] == 2
        recs = {s["labels"]["fn"]: s["value"]
                for s in snap["jit_recompiles_total"]["series"]}
        assert recs["test::step"] == 1

    def test_proxy_forwards_jit_attrs(self):
        wd = CompileWatchdog(registry=MetricsRegistry())
        f = wd.watch(jax.jit(lambda x: x + 1), name="fwd")
        lowered = f.lower(jnp.ones((2,)))    # AOT surface intact
        assert "stablehlo" in lowered.as_text() or lowered.as_text()
        assert callable(f.__wrapped__)

    def test_serving_engine_compiles_each_program_once(self, obs_caplog):
        """The engine's 'ONE statically-shaped program compiles exactly
        once' contract — prompt chunks and decode rows share the unified
        step — watched live across ragged prompts, a prompt long enough
        to span several chunks, and mid-flight admission."""
        from paddle_tpu.models.gpt import GPT_CONFIGS
        from paddle_tpu.serving import Engine, SamplingParams

        with obs_caplog.at_level(logging.WARNING,
                             logger="paddle_tpu.observability"), \
                watchdog_enabled() as wd:
            eng = Engine(GPT_CONFIGS["tiny"], page_size=4, num_pages=64,
                         max_batch_size=2, chunk_len=16)
            eng.generate([[1, 2, 3], [4, 5], list(range(40))],
                         SamplingParams(max_new_tokens=3))
            rep = wd.report()
        assert rep["serving::unified_step"]["compiles"] == 1
        assert rep["serving::unified_step"]["calls"] > 1
        assert not [r for r in obs_caplog.records
                    if r.levelno >= logging.WARNING]


# ---------------------------------------------------------------- profiler
class TestScheduler:
    def test_states_on_right_steps(self):
        s = make_scheduler(wait=1, warmup=2, active=3, repeat=0)
        want = [ProfilerState.CLOSED, ProfilerState.READY,
                ProfilerState.READY, ProfilerState.RECORD,
                ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN]
        assert [s(i) for i in range(6)] == want
        assert [s(i) for i in range(6, 12)] == want     # cycles

    def test_repeat_and_skip_first(self):
        s = make_scheduler(closed=0, ready=0, record=2, repeat=1,
                           skip_first=2)
        assert [s(i) for i in range(6)] == [
            ProfilerState.CLOSED, ProfilerState.CLOSED,
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN,
            ProfilerState.CLOSED, ProfilerState.CLOSED]

    def test_record_required(self):
        with pytest.raises(ValueError):
            make_scheduler(wait=1, warmup=1, active=0)

    def test_profiler_records_only_active_window(self):
        fired = []
        p = Profiler(scheduler=(1, 1, 2, 1), with_device=False,
                     on_trace_ready=lambda pr: fired.append(pr.step_num))
        p.start()
        for i in range(6):
            with RecordEvent(f"step{i}"):
                pass
            p.step()
        p.stop()
        names = {ev[1] for ev in p._events if ev[0] == "X"}
        assert names == {"step2", "step3"}   # active steps only
        assert fired[0] == 3                 # window closed after step 3

    def test_step_without_scheduler_marks_instants(self):
        p = Profiler(with_device=False)
        p.start()
        for _ in range(3):
            p.step()
        p.stop()
        instants = [ev for ev in p._events if ev[0] == "i"]
        assert len(instants) == 4            # start + 3 steps


class TestChromeExport:
    def test_instants_counters_and_track_metadata(self, tmp_path):
        default_registry().gauge("test_occupancy").set(0.75)
        p = Profiler(with_device=False)
        p.start()
        with RecordEvent("span_a"):
            pass
        p.step()
        p.stop()
        out = tmp_path / "trace.json"
        p.export(str(out))
        evs = json.loads(out.read_text())["traceEvents"]
        by_ph = {}
        for e in evs:
            by_ph.setdefault(e["ph"], []).append(e)
        assert any(e["name"] == "span_a" for e in by_ph["X"])
        assert any(e["name"].startswith("ProfilerStep#")
                   for e in by_ph["i"])
        counters = [e for e in by_ph["C"]
                    if e["name"] == "test_occupancy"]
        assert counters and counters[-1]["args"]["test_occupancy"] == 0.75
        meta_names = {e["name"] for e in by_ph["M"]}
        assert {"process_name", "thread_name"} <= meta_names

    def test_record_event_decorator(self):
        @RecordEvent("decorated")
        def work(a, b=1):
            return a + b

        p = Profiler(with_device=False)
        p.start()
        assert work(1, b=2) == 3
        p.stop()
        assert "decorated" in p.summary()


# ------------------------------------------------------------------- timer
class _FakeTime:
    def __init__(self):
        self.t = 0.0

    def perf_counter(self):
        return self.t


class TestBenchmarkWarmupBoundary:
    def test_reader_and_batch_skip_the_same_steps(self, monkeypatch):
        """Regression: the boundary step must contribute reader cost IFF
        it contributes batch cost (the old pre/post-increment convention
        split let them diverge)."""
        import paddle_tpu.profiler.timer as timer_mod

        clk = _FakeTime()
        monkeypatch.setattr(timer_mod, "time", clk)
        from paddle_tpu.profiler.timer import Benchmark

        bm = Benchmark(warmup_steps=1)
        for _ in range(3):
            bm.before_reader()
            clk.t += 0.5                     # reader: 0.5s/step
            bm.after_reader()
            bm.step_start()
            clk.t += 1.0                     # batch: 1.0s/step
            bm.step_end(num_samples=2)
        info = bm.step_info()
        assert info["steps"] == 2            # 3 steps - 1 warmup
        assert info["avg_batch_cost"] == pytest.approx(1.0)
        # reader cost averaged over the SAME 2 counted steps
        assert info["reader_cost"] == pytest.approx(0.5)
        assert info["ips"] == pytest.approx(4 / 2.0)

    def test_dangling_reader_fetch_not_counted(self, monkeypatch):
        """A tail batch fetched but never stepped (loop break) must not
        inflate reader cost."""
        import paddle_tpu.profiler.timer as timer_mod

        clk = _FakeTime()
        monkeypatch.setattr(timer_mod, "time", clk)
        from paddle_tpu.profiler.timer import Benchmark

        bm = Benchmark(warmup_steps=0)
        bm.before_reader()
        clk.t += 0.2
        bm.after_reader()
        bm.step_start()
        clk.t += 1.0
        bm.step_end()
        bm.before_reader()
        clk.t += 99.0                        # fetched, then loop breaks
        bm.after_reader()
        assert bm.step_info()["reader_cost"] == pytest.approx(0.2)


# --------------------------------------------------------- serving client
class TestServingMetricsThinClient:
    def test_registers_into_default_registry(self):
        from paddle_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics()
        m.requests_submitted.inc(2)
        m.ttft.observe(0.1)
        snap = default_registry().snapshot()
        assert snap["serving_requests_submitted_total"]["value"] == 2
        assert snap["serving_ttft_seconds"]["value"]["count"] == 1
        # rebuild = reset: fresh series replace the old ones globally
        m2 = ServingMetrics()
        assert default_registry().snapshot()[
            "serving_requests_submitted_total"]["value"] == 0
        assert m2.snapshot()["requests"]["submitted"] == 0

    def test_isolated_registry(self):
        from paddle_tpu.serving.metrics import ServingMetrics

        reg = MetricsRegistry()
        m = ServingMetrics(registry=reg)
        m.tokens_generated.inc(5)
        assert reg.snapshot()[
            "serving_tokens_generated_total"]["value"] == 5
        snap = m.snapshot()
        assert snap["tokens"]["generated"] == 5
        assert set(snap) == {"requests", "tokens", "queue_wait_s",
                             "ttft_s", "decode_token_s", "page_occupancy",
                             "engine_healthy", "queue_depth",
                             "estimated_drain_s"}


# ------------------------------------------------------------------- bench
class TestBenchTelemetry:
    def test_section_telemetry_embeds_registry_snapshot(self):
        import bench

        default_registry().counter("bench_probe").inc(3)
        out = bench._section_telemetry({"tokens_per_sec": 1.0})
        assert out["metrics"]["bench_probe"]["value"] == 3
        json.dumps(out)


# ----------------------------------------------------------------- hapi
class TestProfilerCallback:
    def test_fit_traces_batches_and_steps(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import ProfilerCallback
        from paddle_tpu.io import Dataset

        class Toy(Dataset):
            def __init__(self, n=16):
                rng = np.random.RandomState(0)
                self.x = rng.randn(n, 4).astype(np.float32)
                self.y = rng.randint(0, 2, (n,)).astype(np.int64)

            def __len__(self):
                return len(self.x)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

        model = paddle.Model(nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                           nn.Linear(8, 2)))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        cb = ProfilerCallback(scheduler=(0, 1, 3, 0), with_device=False)
        model.fit(Toy(), batch_size=4, epochs=1, verbose=0, callbacks=[cb])
        summ = cb.profiler.summary()
        assert "hapi::train_batch" in summ
        assert "hapi::train_step" in summ    # the jitted step span
        instants = [ev for ev in cb.profiler._events if ev[0] == "i"]
        assert instants                      # step boundaries in trace
