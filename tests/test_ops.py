"""OpTest-style conformance harness.

Parity with the reference's op_test.py:289 ``OpTest``: every op's forward is
checked against a NumPy golden, and gradients are checked numerically
(central differences) against the autograd tape — the same two assertions
check_output_with_place/check_grad make.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops


def numeric_grad(fn, x, eps=1e-3):
    """Central-difference jacobian-vector product with all-ones cotangent."""
    x = np.asarray(x, dtype=np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        g[idx] = (np.sum(fn(xp.astype(np.float32))) -
                  np.sum(fn(xm.astype(np.float32)))) / (2 * eps)
        it.iternext()
    return g


def check_op(op_fn, np_fn, shapes, atol=1e-5, grad=True, grad_atol=1e-2,
             **kwargs):
    arrays = [np.random.uniform(0.1, 1.0, s).astype(np.float32) for s in shapes]
    # forward vs numpy golden
    tensors = [paddle.to_tensor(a, stop_gradient=False) for a in arrays]
    out = op_fn(*tensors, **kwargs)
    expected = np_fn(*arrays)
    np.testing.assert_allclose(np.asarray(out.data), expected, atol=atol,
                               rtol=1e-4)
    if grad:
        # analytic (tape) vs numeric grad w.r.t. first input
        loss = ops.sum(out)
        loss.backward()
        analytic = np.asarray(tensors[0].grad.data)

        def f(a0):
            return np.asarray(
                op_fn(paddle.to_tensor(a0),
                      *[paddle.to_tensor(a) for a in arrays[1:]], **kwargs).data)

        numeric = numeric_grad(f, arrays[0])
        np.testing.assert_allclose(analytic, numeric, atol=grad_atol, rtol=1e-2)


class TestElementwise:
    def test_add(self):
        check_op(ops.add, np.add, [(3, 4), (3, 4)])

    def test_subtract(self):
        check_op(ops.subtract, np.subtract, [(3, 4), (3, 4)])

    def test_multiply(self):
        check_op(ops.multiply, np.multiply, [(3, 4), (3, 4)])

    def test_divide(self):
        check_op(ops.divide, np.divide, [(3, 4), (3, 4)])

    def test_broadcast_add(self):
        check_op(ops.add, np.add, [(3, 4), (4,)])

    def test_exp(self):
        check_op(ops.exp, np.exp, [(5, 5)])

    def test_log(self):
        check_op(ops.log, np.log, [(5, 5)])

    def test_sqrt(self):
        check_op(ops.sqrt, np.sqrt, [(5, 5)])

    def test_tanh(self):
        check_op(ops.tanh, np.tanh, [(5, 5)])

    def test_sigmoid(self):
        check_op(ops.sigmoid, lambda x: 1 / (1 + np.exp(-x)), [(5, 5)])

    def test_maximum(self):
        check_op(ops.maximum, np.maximum, [(4, 4), (4, 4)])

    def test_pow(self):
        check_op(lambda x: ops.pow(x, 2.0), lambda x: x ** 2, [(4, 4)])

    def test_clip(self):
        check_op(lambda x: ops.clip(x, 0.3, 0.7),
                 lambda x: np.clip(x, 0.3, 0.7), [(4, 4)], grad=False)

    def test_abs(self):
        check_op(ops.abs, np.abs, [(4, 4)])

    def test_rsqrt(self):
        check_op(ops.rsqrt, lambda x: 1 / np.sqrt(x), [(4, 4)])


class TestReduction:
    def test_sum(self):
        check_op(ops.sum, np.sum, [(3, 4)])

    def test_sum_axis(self):
        check_op(lambda x: ops.sum(x, axis=1),
                 lambda x: np.sum(x, axis=1), [(3, 4)])

    def test_mean(self):
        check_op(ops.mean, np.mean, [(3, 4)])

    def test_max(self):
        check_op(ops.max, np.max, [(3, 4)], grad=False)

    def test_min(self):
        check_op(ops.min, np.min, [(3, 4)], grad=False)

    def test_prod(self):
        check_op(ops.prod, np.prod, [(2, 3)])

    def test_std(self):
        check_op(lambda x: ops.std(x, unbiased=False),
                 lambda x: np.std(x), [(3, 4)])

    def test_logsumexp(self):
        from scipy.special import logsumexp

        check_op(ops.logsumexp, logsumexp, [(3, 4)])

    def test_argmax(self):
        x = np.random.rand(3, 5).astype(np.float32)
        out = ops.argmax(paddle.to_tensor(x), axis=1)
        np.testing.assert_array_equal(np.asarray(out.data), np.argmax(x, 1))


class TestLinalg:
    def test_matmul(self):
        check_op(ops.matmul, np.matmul, [(3, 4), (4, 5)])

    def test_matmul_transpose(self):
        check_op(lambda x, y: ops.matmul(x, y, transpose_y=True),
                 lambda x, y: x @ y.T, [(3, 4), (5, 4)])

    def test_bmm(self):
        check_op(ops.bmm, np.matmul, [(2, 3, 4), (2, 4, 5)])

    def test_einsum(self):
        check_op(lambda x, y: ops.einsum("ij,jk->ik", x, y),
                 lambda x, y: np.einsum("ij,jk->ik", x, y), [(3, 4), (4, 5)])

    def test_norm(self):
        check_op(ops.norm, np.linalg.norm, [(4, 4)])

    def test_inverse(self):
        x = np.random.rand(4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
        out = ops.inverse(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out.data), np.linalg.inv(x),
                                   atol=1e-4)

    def test_cholesky(self):
        a = np.random.rand(4, 4).astype(np.float32)
        x = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        out = ops.cholesky(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out.data), np.linalg.cholesky(x),
                                   atol=1e-4)


class TestManipulation:
    def test_reshape(self):
        check_op(lambda x: ops.reshape(x, [4, 3]),
                 lambda x: x.reshape(4, 3), [(3, 4)])

    def test_transpose(self):
        check_op(lambda x: ops.transpose(x, [1, 0]),
                 lambda x: x.T, [(3, 4)])

    def test_concat(self):
        a = paddle.to_tensor(np.ones((2, 3), np.float32), stop_gradient=False)
        b = paddle.to_tensor(np.zeros((2, 3), np.float32), stop_gradient=False)
        out = ops.concat([a, b], axis=0)
        assert out.shape == [4, 3]
        ops.sum(out * 2.0).backward()
        np.testing.assert_allclose(np.asarray(a.grad.data), 2 * np.ones((2, 3)))

    def test_split(self):
        x = paddle.to_tensor(np.arange(12).reshape(3, 4).astype(np.float32))
        a, b = ops.split(x, 2, axis=1)
        assert a.shape == [3, 2] and b.shape == [3, 2]

    def test_split_sections(self):
        x = paddle.to_tensor(np.arange(12).reshape(3, 4).astype(np.float32))
        a, b, c = ops.split(x, [1, 2, -1], axis=1)
        assert a.shape == [3, 1] and b.shape == [3, 2] and c.shape == [3, 1]

    def test_squeeze_unsqueeze(self):
        x = paddle.to_tensor(np.ones((1, 3, 1), np.float32))
        assert ops.squeeze(x).shape == [3]
        assert ops.unsqueeze(x, 0).shape == [1, 1, 3, 1]

    def test_gather(self):
        x = np.random.rand(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4])
        out = ops.gather(paddle.to_tensor(x), paddle.to_tensor(idx), axis=0)
        np.testing.assert_allclose(np.asarray(out.data), x[idx])

    def test_where(self):
        check_op(lambda x, y: ops.where(x > 0.5, x, y),
                 lambda x, y: np.where(x > 0.5, x, y), [(4, 4), (4, 4)],
                 grad=False)

    def test_stack(self):
        xs = [np.random.rand(2, 3).astype(np.float32) for _ in range(3)]
        out = ops.stack([paddle.to_tensor(x) for x in xs], axis=0)
        np.testing.assert_allclose(np.asarray(out.data), np.stack(xs))

    def test_pad(self):
        x = np.random.rand(2, 3).astype(np.float32)
        out = ops.pad(paddle.to_tensor(x), [1, 1], value=0.0)
        assert out.shape == [2, 5]

    def test_tile(self):
        check_op(lambda x: ops.tile(x, [2, 2]),
                 lambda x: np.tile(x, (2, 2)), [(2, 3)])

    def test_cast(self):
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        assert ops.cast(x, "int32").dtype == np.int32


class TestActivation:
    def test_relu(self):
        x = np.random.randn(4, 4).astype(np.float32)
        out = ops.relu(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out.data), np.maximum(x, 0))

    def test_gelu(self):
        from scipy.stats import norm as scipy_norm

        x = np.random.randn(4, 4).astype(np.float32)
        out = ops.gelu(paddle.to_tensor(x))
        expected = x * scipy_norm.cdf(x)
        np.testing.assert_allclose(np.asarray(out.data), expected, atol=1e-5)

    def test_softmax(self):
        x = np.random.randn(3, 5).astype(np.float32)
        out = ops.softmax(paddle.to_tensor(x))
        e = np.exp(x - x.max(-1, keepdims=True))
        np.testing.assert_allclose(np.asarray(out.data), e / e.sum(-1, keepdims=True),
                                   atol=1e-6)

    def test_leaky_relu(self):
        x = np.random.randn(4, 4).astype(np.float32)
        out = ops.leaky_relu(paddle.to_tensor(x), 0.1)
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.where(x >= 0, x, 0.1 * x), atol=1e-6)


class TestLoss:
    def test_cross_entropy(self):
        logits = np.random.randn(4, 10).astype(np.float32)
        labels = np.array([1, 3, 5, 7])
        out = ops.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
        # numpy golden
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        expected = -np.mean(np.log(p[np.arange(4), labels]))
        np.testing.assert_allclose(float(out.data), expected, atol=1e-5)

    def test_cross_entropy_grad(self):
        logits = paddle.to_tensor(np.random.randn(4, 10).astype(np.float32),
                                  stop_gradient=False)
        labels = paddle.to_tensor(np.array([1, 3, 5, 7]))
        loss = ops.cross_entropy(logits, labels)
        loss.backward()
        assert logits.grad is not None
        np.testing.assert_allclose(np.asarray(logits.grad.data).sum(), 0.0,
                                   atol=1e-5)

    def test_mse(self):
        check_op(ops.mse_loss, lambda a, b: np.mean((a - b) ** 2),
                 [(4, 4), (4, 4)])

    def test_bce_with_logits(self):
        x = np.random.randn(8).astype(np.float32)
        y = np.random.randint(0, 2, 8).astype(np.float32)
        out = ops.binary_cross_entropy_with_logits(
            paddle.to_tensor(x), paddle.to_tensor(y))
        p = 1 / (1 + np.exp(-x))
        expected = -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))
        np.testing.assert_allclose(float(out.data), expected, atol=1e-5)


class TestConvPool:
    def test_conv2d_shape(self):
        x = paddle.to_tensor(np.random.rand(2, 3, 8, 8).astype(np.float32))
        w = paddle.to_tensor(np.random.rand(4, 3, 3, 3).astype(np.float32))
        out = ops.conv2d(x, w, padding=1)
        assert out.shape == [2, 4, 8, 8]

    def test_conv2d_golden(self):
        # golden: 1x1 conv == matmul over channels
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        w = np.random.rand(5, 3, 1, 1).astype(np.float32)
        out = ops.conv2d(paddle.to_tensor(x), paddle.to_tensor(w))
        expected = np.einsum("nchw,oc->nohw", x, w[:, :, 0, 0])
        np.testing.assert_allclose(np.asarray(out.data), expected, atol=1e-4)

    def test_conv2d_grad(self):
        x = paddle.to_tensor(np.random.rand(1, 2, 5, 5).astype(np.float32),
                             stop_gradient=False)
        w = paddle.to_tensor(np.random.rand(3, 2, 3, 3).astype(np.float32),
                             stop_gradient=False)
        out = ops.conv2d(x, w, padding=1)
        ops.sum(out).backward()
        assert x.grad.shape == [1, 2, 5, 5]
        assert w.grad.shape == [3, 2, 3, 3]

    def test_maxpool(self):
        x = np.random.rand(1, 1, 4, 4).astype(np.float32)
        out = ops.max_pool2d(paddle.to_tensor(x), 2, 2)
        expected = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
        np.testing.assert_allclose(np.asarray(out.data), expected)

    def test_avgpool(self):
        x = np.random.rand(1, 1, 4, 4).astype(np.float32)
        out = ops.avg_pool2d(paddle.to_tensor(x), 2, 2)
        expected = x.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
        np.testing.assert_allclose(np.asarray(out.data), expected, atol=1e-6)

    def test_layer_norm(self):
        x = np.random.rand(2, 3, 8).astype(np.float32)
        out = ops.layer_norm(paddle.to_tensor(x))
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        expected = (x - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(out.data), expected, atol=1e-5)

    def test_batch_norm_train(self):
        x = np.random.rand(4, 3, 2, 2).astype(np.float32)
        out, mean, var = ops.batch_norm_train(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(mean.data),
                                   x.mean(axis=(0, 2, 3)), atol=1e-5)

    def test_embedding(self):
        w = np.random.rand(10, 4).astype(np.float32)
        ids = np.array([[1, 2], [3, 4]])
        out = ops.embedding(paddle.to_tensor(ids), paddle.to_tensor(w))
        np.testing.assert_allclose(np.asarray(out.data), w[ids])


class TestSearchSort:
    def test_topk(self):
        x = np.random.rand(3, 10).astype(np.float32)
        vals, idx = ops.topk(paddle.to_tensor(x), k=3)
        expected = np.sort(x, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(np.asarray(vals.data), expected, atol=1e-6)

    def test_sort(self):
        x = np.random.rand(10).astype(np.float32)
        out = ops.sort(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out.data), np.sort(x))

    def test_argsort(self):
        x = np.random.rand(10).astype(np.float32)
        out = ops.argsort(paddle.to_tensor(x))
        np.testing.assert_array_equal(np.asarray(out.data), np.argsort(x))


class TestAttention:
    def test_sdpa_matches_naive(self):
        q = np.random.randn(2, 4, 8, 16).astype(np.float32)
        k = np.random.randn(2, 4, 8, 16).astype(np.float32)
        v = np.random.randn(2, 4, 8, 16).astype(np.float32)
        out = ops.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            use_flash=False)
        # numpy golden
        logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(16)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        expected = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(np.asarray(out.data), expected, atol=1e-4)

    def test_sdpa_causal(self):
        q = np.random.randn(1, 2, 6, 8).astype(np.float32)
        out = ops.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            is_causal=True, use_flash=False)
        assert out.shape == [1, 2, 6, 8]
