"""Optimizer tests: numeric parity with reference update rules + end-to-end
convergence (the reference's test_{sgd,adam,momentum}_op + dist training
loss-descent assertions)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _quadratic_setup():
    w = paddle.Parameter(np.array([5.0, -3.0], np.float32))
    def loss_fn():
        return (w * w).sum()
    return w, loss_fn


def test_sgd_matches_formula():
    w, loss_fn = _quadratic_setup()
    opt = optimizer.SGD(learning_rate=0.1, parameters=[w])
    loss_fn().backward()
    opt.step()
    np.testing.assert_allclose(np.asarray(w.data), [5 - 0.1 * 10, -3 + 0.1 * 6],
                               atol=1e-6)


def test_momentum_matches_formula():
    w, loss_fn = _quadratic_setup()
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9, parameters=[w])
    for _ in range(2):
        loss_fn().backward()
        opt.step()
        opt.clear_grad()
    # manual: v1=g1; w1=w0-lr*v1; v2=0.9v1+g2; w2=w1-lr*v2
    w0 = np.array([5.0, -3.0])
    v = 2 * w0
    w1 = w0 - 0.1 * v
    v = 0.9 * v + 2 * w1
    w2 = w1 - 0.1 * v
    np.testing.assert_allclose(np.asarray(w.data), w2, atol=1e-5)


def test_adam_matches_reference_formula():
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = optimizer.Adam(learning_rate=0.1, parameters=[w])
    (w * 3.0).sum().backward()
    opt.step()
    g = 3.0
    m = 0.1 * g
    v = 0.001 * g * g
    mh = m / 0.1
    vh = v / 0.001
    expected = 1.0 - 0.1 * mh / (np.sqrt(vh) + 1e-8)
    np.testing.assert_allclose(np.asarray(w.data), [expected], atol=1e-5)


def test_adamw_decoupled_decay():
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = optimizer.AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[w])
    (w * 3.0).sum().backward()
    opt.step()
    g = 3.0
    mh, vh = g, g * g
    expected = 1.0 - 0.1 * mh / (np.sqrt(vh) + 1e-8) - 0.1 * 0.5 * 1.0
    np.testing.assert_allclose(np.asarray(w.data), [expected], atol=1e-5)


def test_convergence_linear_regression():
    np.random.seed(0)
    true_w = np.array([[2.0], [-1.0]], np.float32)
    X = np.random.rand(64, 2).astype(np.float32)
    y = X @ true_w
    model = nn.Linear(2, 1)
    opt = optimizer.Adam(learning_rate=0.05, parameters=model.parameters())
    loss_fn = nn.MSELoss()
    for _ in range(300):
        loss = loss_fn(model(paddle.to_tensor(X)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(np.asarray(model.weight.data), true_w, atol=0.05)


def test_grad_clip_global_norm():
    w = paddle.Parameter(np.array([3.0, 4.0], np.float32))
    clip = optimizer.ClipGradByGlobalNorm(1.0)
    opt = optimizer.SGD(learning_rate=1.0, parameters=[w], grad_clip=clip)
    (w * paddle.to_tensor(np.array([3.0, 4.0], np.float32))).sum().backward()
    # grad = [3,4], norm 5 → clipped to [0.6, 0.8]
    opt.step()
    np.testing.assert_allclose(np.asarray(w.data), [3 - 0.6, 4 - 0.8], atol=1e-5)


def test_lr_scheduler_step():
    sched = optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    opt = optimizer.SGD(learning_rate=sched, parameters=[paddle.Parameter(np.zeros(1, np.float32))])
    lrs = []
    for _ in range(5):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])


def test_warmup_scheduler():
    sched = optimizer.lr.LinearWarmup(learning_rate=0.1, warmup_steps=5,
                                      start_lr=0.0, end_lr=0.1)
    vals = []
    for _ in range(7):
        vals.append(sched())
        sched.step()
    assert vals[0] == 0.0
    np.testing.assert_allclose(vals[5], 0.1, atol=1e-6)


def test_cosine_scheduler():
    sched = optimizer.lr.CosineAnnealingDecay(learning_rate=0.1, T_max=10)
    sched.step(10)
    np.testing.assert_allclose(sched(), 0.0, atol=1e-8)


def test_optimizer_state_dict_roundtrip():
    w = paddle.Parameter(np.ones(3, np.float32))
    opt = optimizer.Adam(learning_rate=0.1, parameters=[w])
    (w * 2).sum().backward()
    opt.step()
    state = opt.state_dict()
    w2 = paddle.Parameter(np.ones(3, np.float32))
    opt2 = optimizer.Adam(learning_rate=0.1, parameters=[w2])
    opt2.set_state_dict(state)
    assert opt2._step_count == 1
    np.testing.assert_allclose(
        np.asarray(opt2._slots[id(w2)]["moment1"]),
        np.asarray(opt._slots[id(w)]["moment1"]))


def test_functional_apply_gradients_matches_eager():
    import jax.numpy as jnp

    w = paddle.Parameter(np.array([2.0, 2.0], np.float32))
    opt = optimizer.Adam(learning_rate=0.1, parameters=[w])
    g = np.array([0.5, -0.5], np.float32)

    # functional path
    params = {"w": jnp.asarray(np.array([2.0, 2.0], np.float32))}
    state = opt.init_state(params)
    new_params, _ = opt.apply_gradients(params, {"w": jnp.asarray(g)}, state)

    # eager path
    w.grad = paddle.to_tensor(g)
    opt.step()
    np.testing.assert_allclose(np.asarray(w.data), np.asarray(new_params["w"]),
                               atol=1e-6)


def test_multi_precision_master_weights():
    w = paddle.Parameter(np.ones(4, np.float32))
    w.data = w.data.astype(paddle.bfloat16)
    opt = optimizer.Adam(learning_rate=1e-3, parameters=[w], multi_precision=True)
    for _ in range(3):
        (w.astype("float32") * 2).sum().backward()
        opt.step()
        opt.clear_grad()
    master = opt._master_weights[id(w)]
    assert master.dtype == np.float32
    np.testing.assert_allclose(np.asarray(master), 1.0 - 3e-3, atol=1e-5)
