"""Pipeline user-API tests — the reference's hybrid_parallel_pp_alexnet.py
scenario: an arbitrary (CNN) Layer list staged over pp must train to the
same losses as the single-device run.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.pp_layers import (LayerDesc, PipelineEngine,
                                              PipelineLayer, SegmentLayers,
                                              SharedLayerDesc)


def _cnn_descs(seed=7):
    """AlexNet-style conv stack: conv/pool features then FC classifier."""
    return [
        LayerDesc(nn.Conv2D, 1, 6, 5, padding=2),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.MaxPool2D, kernel_size=2, stride=2),
        LayerDesc(nn.Conv2D, 6, 16, 5),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.MaxPool2D, kernel_size=2, stride=2),
        LayerDesc(nn.Flatten),
        LayerDesc(nn.Linear, 16 * 5 * 5, 64),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, 64, 10),
    ]


def _batch(bs=8):
    rng = np.random.RandomState(0)
    x = rng.randn(bs, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, (bs,)).astype(np.int32)
    return x, y


class TestSegmentLayers:
    def test_uniform(self):
        layers = [nn.Linear(4, 4) for _ in range(6)]
        assert SegmentLayers(layers, 2).do_segment() == [0, 3, 6]
        assert SegmentLayers(layers, 3).do_segment() == [0, 2, 4, 6]

    def test_parameter_balanced(self):
        layers = [nn.Linear(64, 64), nn.Linear(4, 4), nn.Linear(4, 4),
                  nn.Linear(4, 4)]
        bounds = SegmentLayers(layers, 2, method="parameter").do_segment()
        # the big first layer should sit alone in stage 0
        assert bounds == [0, 1, 4]

    def test_every_stage_nonempty(self):
        layers = [nn.Linear(4, 4) for _ in range(5)]
        for parts in (2, 3, 4, 5):
            b = SegmentLayers(layers, parts).do_segment()
            assert len(b) == parts + 1
            assert all(b[i] < b[i + 1] for i in range(parts))


class TestPipelineLayer:
    def test_forward_matches_sequential(self):
        paddle.seed(42)
        pl = PipelineLayer(_cnn_descs(), num_stages=2,
                           loss_fn=nn.CrossEntropyLoss())
        x, _ = _batch(4)
        out = pl(paddle.to_tensor(x))
        # run the same layers manually
        ref = paddle.to_tensor(x)
        for layer in pl.run_funcs:
            ref = layer(ref)
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(ref.data), atol=1e-6)

    def test_shared_desc_ties_params(self):
        paddle.seed(0)
        descs = [
            SharedLayerDesc("emb", nn.Linear, 8, 8),
            LayerDesc(nn.ReLU),
            SharedLayerDesc("emb", nn.Linear, 8, 8),
        ]
        pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.MSELoss())
        assert pl.run_funcs[0] is pl.run_funcs[2]


@pytest.mark.slow
class TestPipelineEngine:
    @pytest.fixture(scope="class")
    def pp1_losses(self):
        paddle.seed(123)
        pl = PipelineLayer(_cnn_descs(), num_stages=1,
                           loss_fn=nn.CrossEntropyLoss())
        eng = PipelineEngine(pl, num_microbatches=4,
                             devices=jax.devices()[:1])
        x, y = _batch()
        state, losses = None, []
        for _ in range(3):
            state, loss = eng.train_batch(x, y, state, lr=0.01)
            losses.append(float(loss))
        return losses

    def test_pp1_loss_sane_and_decreasing(self, pp1_losses):
        assert all(np.isfinite(pp1_losses))
        assert pp1_losses[-1] < pp1_losses[0]

    def test_pp2_matches_single_device(self, pp1_losses):
        paddle.seed(123)   # identical init
        pl = PipelineLayer(_cnn_descs(), num_stages=2,
                           loss_fn=nn.CrossEntropyLoss())
        eng = PipelineEngine(pl, num_microbatches=4,
                             devices=jax.devices()[:2])
        x, y = _batch()
        state, losses = None, []
        for _ in range(3):
            state, loss = eng.train_batch(x, y, state, lr=0.01)
            losses.append(float(loss))
        np.testing.assert_allclose(losses, pp1_losses, atol=2e-4, rtol=1e-4)

    def test_pp4_param_segmented(self, pp1_losses):
        paddle.seed(123)
        pl = PipelineLayer(_cnn_descs(), num_stages=4,
                           loss_fn=nn.CrossEntropyLoss(),
                           seg_method="parameter")
        eng = PipelineEngine(pl, num_microbatches=4,
                             devices=jax.devices()[:4])
        x, y = _batch()
        state, losses = None, []
        for _ in range(3):
            state, loss = eng.train_batch(x, y, state, lr=0.01)
            losses.append(float(loss))
        np.testing.assert_allclose(losses, pp1_losses, atol=2e-4, rtol=1e-4)

    def test_pp4_params_sharded_quarter_memory(self):
        """VERDICT r3 item 5 acceptance: with pp=4, each rank holds ~1/4
        of the params (its padded stage slice), not a full replica."""
        paddle.seed(5)
        layers = [LayerDesc(nn.Linear, 128, 128) for _ in range(8)]
        pl = PipelineLayer(layers, num_stages=4, loss_fn=nn.MSELoss(),
                           seg_method="parameter")
        eng = PipelineEngine(pl, num_microbatches=4,
                             devices=jax.devices()[:4])
        x = np.random.RandomState(0).randn(8, 128).astype(np.float32)
        y = np.zeros((8, 128), np.float32)
        state, _ = eng.train_batch(x, y, lr=0.01)
        flat = state["flat"]
        total_param_bytes = sum(
            int(np.prod(p.shape)) * 4
            for st in eng.state() for p in st.values())
        shard_bytes = flat.addressable_shards[0].data.nbytes
        # balanced stages: per-rank slice ~ total/4 (+ padding slack)
        assert shard_bytes <= total_param_bytes / 4 * 1.2, \
            (shard_bytes, total_param_bytes)
        # and the stacked container itself is genuinely sharded over pp
        assert len({s.device for s in flat.addressable_shards}) == 4

    def test_shared_layer_grads_allreduced(self, pp1_losses):
        """Tied layer on first and last stage: trains identically to the
        single-stage run (grad psum over pp = the reference's
        allreduce_shared_weight_gradients)."""
        def descs():
            return [
                SharedLayerDesc("tied", nn.Linear, 16, 16),
                LayerDesc(nn.ReLU),
                LayerDesc(nn.Linear, 16, 16),
                SharedLayerDesc("tied", nn.Linear, 16, 16),
            ]

        def run(stages, ndev):
            paddle.seed(77)
            pl = PipelineLayer(descs(), num_stages=stages,
                               loss_fn=nn.MSELoss())
            eng = PipelineEngine(pl, num_microbatches=2,
                                 devices=jax.devices()[:ndev])
            rng = np.random.RandomState(1)
            x = rng.randn(4, 16).astype(np.float32)
            y = rng.randn(4, 16).astype(np.float32)
            state, losses = None, []
            for _ in range(3):
                state, loss = eng.train_batch(x, y, state, lr=0.05)
                losses.append(float(loss))
            return losses

        np.testing.assert_allclose(run(2, 2), run(1, 1),
                                   atol=1e-5, rtol=1e-5)

    def test_unpack_round_trips_paramless_layers(self):
        """unpack() must yield {} (not None) for ReLU-style layers so
        load_state(unpack(packed)) restores checkpoints."""
        paddle.seed(3)
        pl = PipelineLayer([LayerDesc(nn.Linear, 8, 8),
                            LayerDesc(nn.ReLU),
                            LayerDesc(nn.Linear, 8, 8),
                            LayerDesc(nn.ReLU)],
                           num_stages=2, loss_fn=nn.MSELoss())
        eng = PipelineEngine(pl, num_microbatches=2,
                             devices=jax.devices()[:2])
        x = np.ones((4, 8), np.float32)
        y = np.zeros((4, 8), np.float32)
        state, _ = eng.train_batch(x, y, lr=0.1)
        logical = eng.unpack(state)
        eng.load_state(logical)            # must not crash on ReLU
        w_after = np.asarray(dict(pl.run_funcs[0].named_parameters())
                             ["weight"].data)
        np.testing.assert_allclose(
            w_after, np.asarray(logical[0]["weight"]), atol=1e-6)
