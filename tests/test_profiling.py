"""Continuous profiling matrix — phase attribution, windowed queries,
anomaly-triggered high-rate capture (SLO page / health anomaly), trace
linkage, profile diffing, the ``/profilez`` endpoint, the fleet
``/slo?fleet=1`` gossip fold, and the subprocess overhead smoke gating
the documented <1% always-on bound.

Everything except the overhead smoke runs on a manual clock:
``sample_once`` is the inline driver, so a test decides exactly when a
walk happens and what phase the walked thread is in — sample counts
and phase slices are deterministic for the *calling* thread (other
live threads contribute to their own phases, never ours).
"""
import json
import threading
import time
import urllib.error
import urllib.request

from paddle_tpu.observability.exporter import start_telemetry_server
from paddle_tpu.observability.health import HealthMonitor
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.profiling import (PROFILING_SERIES,
                                                StackSampler,
                                                current_phase,
                                                diff_profiles, phase)
from paddle_tpu.observability.slo import SLO, BurnRateAlert, SLOEngine
from paddle_tpu.observability.timeseries import TimeSeriesStore
from paddle_tpu.observability.tracing import Tracer, activate


class _ManualClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _sampler(clock, **kw):
    kw.setdefault("registry", MetricsRegistry())
    return StackSampler(clock=clock, **kw)


# ------------------------------------------------------- phase markers


class TestPhaseMarkers:
    def test_nesting_is_innermost_wins_and_cleans_up(self):
        assert current_phase() is None
        with phase("decode"):
            assert current_phase() == "decode"
            with phase("checkpoint"):
                assert current_phase() == "checkpoint"
            assert current_phase() == "decode"
        assert current_phase() is None

    def test_cross_thread_read(self):
        seen = {}
        ready, done = threading.Event(), threading.Event()

        def work():
            with phase("prefill_chunk"):
                ready.set()
                done.wait(5.0)

        t = threading.Thread(target=work, daemon=True)
        t.start()
        assert ready.wait(5.0)
        seen["phase"] = current_phase(t.ident)
        done.set()
        t.join(5.0)
        assert seen["phase"] == "prefill_chunk"
        assert current_phase(t.ident) is None       # registry cleaned


# --------------------------------------------------------- sampler core


class TestSamplerCore:
    def test_phase_attribution_and_sum_invariant(self):
        clock = _ManualClock()
        s = _sampler(clock, interval_s=0.1)
        with phase("decode"):
            for _ in range(3):
                clock.advance(0.1)
                s.sample_once()
        with phase("checkpoint"):
            for _ in range(2):
                clock.advance(0.1)
                s.sample_once()
        clock.advance(0.1)
        s.sample_once()                             # unattributed walk
        prof = s.profile()
        assert prof["by_phase"]["decode"]["samples"] == 3
        assert prof["by_phase"]["checkpoint"]["samples"] == 2
        assert abs(prof["by_phase"]["decode"]["seconds"] - 0.3) < 1e-9
        # acceptance: phase slices sum EXACTLY to the sampled wall time
        assert abs(sum(v["seconds"] for v in prof["by_phase"].values())
                   - prof["sampled_seconds"]) < 1e-9
        assert sum(v["samples"] for v in prof["by_phase"].values()) \
            == prof["samples"]
        # the calling thread's stack is interned and counted
        assert any("test_profiling" in k for k in prof["stacks"])

    def test_windowed_selection_and_retention(self):
        clock = _ManualClock()
        s = _sampler(clock, interval_s=1.0, retention_s=10.0)
        with phase("decode"):
            for _ in range(6):
                clock.advance(1.0)
                s.sample_once()                     # walks at t=1..6
        prof = s.profile(window_seconds=2.5, end_s=6.0)
        # window (3.5, 6.0] keeps the walks at t=4,5,6
        assert prof["by_phase"]["decode"]["samples"] == 3
        full = s.profile()
        assert full["by_phase"]["decode"]["samples"] == 6
        # retention: walks older than retention_s are evicted
        clock.advance(20.0)
        s.sample_once()
        assert s.profile()["by_phase"].get("decode") is None

    def test_phase_filter_restricts_stacks_not_slices(self):
        clock = _ManualClock()
        s = _sampler(clock)
        with phase("decode"):
            clock.advance(0.1)
            s.sample_once()
        with phase("checkpoint"):
            clock.advance(0.1)
            s.sample_once()
        prof = s.profile(phase="decode")
        # slices still cover everything (the invariant holds) ...
        assert "checkpoint" in prof["by_phase"]
        # ... but every aggregated stack belongs to the filtered slice
        assert prof["stacks"]
        assert sum(v["samples"] for v in prof["stacks"].values()) \
            <= prof["by_phase"]["decode"]["samples"] * 2

    def test_ambient_span_fallback_attribution(self):
        clock = _ManualClock()
        tracer = Tracer()
        s = _sampler(clock, tracer=tracer)
        span = tracer.start_trace("decode[3]")
        with activate(span):
            clock.advance(0.1)
            s.sample_once()
        span.end()
        prof = s.profile()
        assert prof["by_phase"]["decode"]["samples"] == 1
        # the sample carries the ambient trace_id (stored per row)
        with s._lock:
            tids = {row[3] for row in s._samples}
        assert span.trace_id in tids

    def test_stack_table_overflow_collapses_to_sentinel(self):
        clock = _ManualClock()
        s = _sampler(clock, max_stacks=1)
        for _ in range(3):
            clock.advance(0.1)
            s.sample_once()
        prof = s.profile()
        assert s.stats()["stacks_interned"] <= 2    # 1 real + sentinel
        if len(prof["stacks"]) > 1:
            assert "(stack-table-full)" in prof["stacks"]

    def test_nothing_on_import_thread_opt_in(self):
        s = _sampler(None, interval_s=0.005)
        assert s.running is False
        with s:
            assert s.running is True
            deadline = time.perf_counter() + 5.0
            while s.stats()["lifetime_samples"] == 0 and \
                    time.perf_counter() < deadline:
                time.sleep(0.005)
        assert s.running is False
        assert s.stats()["lifetime_samples"] > 0
        assert s.stats()["overhead_ratio"] is not None


# ----------------------------------------- anomaly-triggered capture


class TestCapture:
    def test_capture_escalates_weights_links_trace_and_suppresses(self):
        clock = _ManualClock()
        tracer = Tracer()
        s = _sampler(clock, interval_s=0.1, capture_interval_s=0.01,
                     tracer=tracer)
        anom = tracer.start_trace("health::slow_step",
                                  attributes={"retain": True})
        anom.end()
        assert s.trigger_capture("health", detail="slow_step",
                                 context=anom.context(), window_s=0.5)
        # a second trigger while the window is open is suppressed
        assert s.trigger_capture("health", detail="again") is False
        assert s.stats()["captures_suppressed"] == 1
        assert s.profile()["capture_active"] is True
        with phase("decode"):
            for _ in range(4):
                clock.advance(0.1)
                s.sample_once()                     # inside the window
            clock.advance(0.3)
            s.sample_once()                         # closes the window
        cap = s.last_capture()
        assert cap is not None and cap["trigger"] == "health"
        assert cap["detail"] == "slow_step"
        assert cap["by_phase"]["decode"] == 4       # closing walk is out
        assert cap["samples"] >= 4
        assert cap["hot"], cap
        # trace linkage: the capture CONTINUES the anomaly's trace
        assert cap["trace_id"] == anom.trace_id
        entries = [t for t in tracer.traces()
                   if t["name"] == "profiling::capture"]
        assert len(entries) == 1
        assert entries[0]["trace_id"] == anom.trace_id
        assert entries[0]["retained"] == "flagged"  # tail-retained
        assert entries[0]["spans"][0]["attributes"]["trigger"] == "health"
        # escalated weights: 4 walks x 10ms inside, 1 x 100ms after
        prof = s.profile()
        assert abs(prof["by_phase"]["decode"]["seconds"]
                   - (4 * 0.01 + 0.1)) < 1e-9
        assert s.profile()["capture_active"] is False

    def test_capture_without_context_or_tracer_still_records(self):
        clock = _ManualClock()
        s = _sampler(clock)                          # no tracer at all
        assert s.trigger_capture("manual", window_s=0.2)
        clock.advance(0.1)
        s.sample_once()
        clock.advance(0.2)
        s.sample_once()
        cap = s.last_capture()
        assert cap["trigger"] == "manual"
        assert cap["trace_id"] is None and cap.get("span_id") is None

    def test_slo_page_fire_arms_capture_linked_to_transition_span(self):
        """Acceptance: a firing page escalates the sampler and the
        finished capture shares the ``slo::`` transition's trace."""
        clock = _ManualClock()
        tracer = Tracer()
        reg = MetricsRegistry()
        req, bad = reg.counter("req_total"), reg.counter("bad_total")
        store = TimeSeriesStore(registry=reg, clock=clock)
        s = _sampler(clock, tracer=tracer, registry=reg)
        engine = SLOEngine(
            store,
            [SLO("availability", target=0.9, bad="bad_total",
                 total="req_total",
                 alerts=(BurnRateAlert("page", burn_rate_threshold=5.0,
                                       long_window_seconds=4.0,
                                       short_window_seconds=1.0,
                                       clear_after_seconds=1.0),),
                 budget_window_seconds=60.0)],
            registry=reg, tracer=tracer, clock=clock, profiler=s)

        def beat(n_req, n_bad):
            clock.advance(0.5)
            req.inc(n_req)
            bad.inc(n_bad)
            store.scrape_once()
            return engine.evaluate()

        for _ in range(8):
            beat(10, 0)
        fired = []
        for _ in range(10):
            fired = [t for t in beat(10, 10)
                     if t["transition"] == "fire"]
            if fired:
                break
        assert fired, "storm never fired the page"
        assert engine.max_burn_rate() > 5.0
        assert s.profile()["capture_active"] is True
        cap_metric = reg.counter(
            "profiling_captures_total",
            "anomaly-triggered capture windows armed, by trigger",
            labelnames=("trigger",))
        assert cap_metric.labels(trigger="slo_page").value == 1
        clock.advance(s.capture_window_s + 0.1)
        s.sample_once()                             # close the window
        cap = s.last_capture()
        assert cap["trigger"] == "slo_page"
        assert cap["detail"] == "availability"
        slo_traces = [t for t in tracer.traces()
                      if t["name"] == "slo::availability"]
        assert cap["trace_id"] in {t["trace_id"] for t in slo_traces}
        assert any(t["name"] == "profiling::capture"
                   and t["trace_id"] == cap["trace_id"]
                   for t in tracer.traces())

    def test_injected_slow_step_anomaly_triggers_capture(self):
        """Acceptance: an injected slow-step anomaly (HealthMonitor's
        ``step_time_outlier``) yields a retained high-rate capture."""
        clock = _ManualClock()
        tracer = Tracer()
        s = _sampler(clock, tracer=tracer)
        mon = HealthMonitor(window=20, min_samples=4, skip_first_steps=0,
                            registry=MetricsRegistry(), tracer=tracer,
                            clock=clock, profiler=s)
        mon.on_train_begin()
        for step in range(6):
            mon.on_train_batch_begin(step)
            clock.advance(0.1)                      # steady 100ms steps
            mon.on_train_batch_end(step, logs={"loss": 1.0})
        mon.on_train_batch_begin(6)
        clock.advance(1.0)                          # the injected stall
        mon.on_train_batch_end(6, logs={"loss": 1.0})
        assert [k for k, _, _ in mon.events] == ["step_time_outlier"]
        assert s.profile()["capture_active"] is True
        clock.advance(s.capture_window_s + 0.1)
        s.sample_once()
        cap = s.last_capture()
        assert cap["trigger"] == "health"
        assert cap["detail"] == "step_time_outlier"
        health = [t for t in tracer.traces()
                  if t["name"] == "health::step_time_outlier"]
        assert cap["trace_id"] in {t["trace_id"] for t in health}
        flagged = [t for t in tracer.traces()
                   if t["name"] == "profiling::capture"]
        assert flagged and flagged[0]["retained"] == "flagged"


# --------------------------------------------------- diffing + flamegraph


class TestDiffAndFlamegraph:
    def test_diff_profiles_normalizes_and_ranks(self):
        cur = {"samples": 10, "window_seconds": 60,
               "stacks": {"main;a;hot": {"samples": 8},
                          "main;b": {"samples": 2}},
               "by_phase": {"decode": {"samples": 10}}}
        base = {"samples": 20, "window_seconds": 60,
                "stacks": {"main;a;hot": {"samples": 4},
                           "main;b": {"samples": 12},
                           "main;gone": {"samples": 4}},
                "by_phase": {"decode": {"samples": 8},
                             "idle": {"samples": 12}}}
        d = diff_profiles(cur, base)
        assert d["samples"] == {"current": 10, "baseline": 20}
        top = d["stacks"][0]
        assert top["stack"] == "main;a;hot"         # 0.8 - 0.2 = +0.6
        assert abs(top["delta"] - 0.6) < 1e-6
        assert d["stacks"][-1]["delta"] < 0         # shrunk stacks last
        gone = [r for r in d["stacks"] if r["stack"] == "main;gone"]
        assert gone and gone[0]["fraction"] == 0.0
        ph = {r["phase"]: r["delta"] for r in d["by_phase"]}
        assert ph["decode"] > 0 and ph["idle"] < 0

    def test_sampler_diff_compares_adjacent_windows(self):
        clock = _ManualClock()
        s = _sampler(clock, interval_s=1.0)
        with phase("old_hot"):
            for _ in range(4):
                clock.advance(1.0)
                s.sample_once()                     # t=1..4
        with phase("new_hot"):
            for _ in range(4):
                clock.advance(1.0)
                s.sample_once()                     # t=5..8
        d = s.diff(window_seconds=4.0, end_s=8.0)
        ph = {r["phase"]: r["delta"] for r in d["by_phase"]}
        assert ph["new_hot"] > 0 and ph["old_hot"] < 0

    def test_flamegraph_collapsed_text(self):
        clock = _ManualClock()
        s = _sampler(clock)
        with phase("decode"):
            clock.advance(0.1)
            s.sample_once()
        text = s.flamegraph()
        assert text.endswith("\n")
        for line in text.strip().splitlines():
            stack, count = line.rsplit(" ", 1)
            assert ";" in stack and int(count) >= 1


# ------------------------------------------------------ /profilez wire


class TestProfilezEndpoint:
    def test_profilez_json_collapsed_and_params(self):
        clock = _ManualClock()
        s = _sampler(clock)
        with phase("decode"):
            for _ in range(3):
                clock.advance(0.1)
                s.sample_once()
        server = start_telemetry_server(port=0, profiler=s)
        try:
            status, body = _get(server.url + "/profilez")
            assert status == 200
            prof = json.loads(body)
            assert prof["by_phase"]["decode"]["samples"] == 3
            assert abs(sum(v["seconds"]
                           for v in prof["by_phase"].values())
                       - prof["sampled_seconds"]) < 1e-9
            status, text = _get(server.url
                                + "/profilez?format=collapsed")
            assert status == 200
            assert all(line.rsplit(" ", 1)[1].isdigit()
                       for line in text.strip().splitlines())
            status, body = _get(
                server.url + "/profilez?window_seconds=0.05&phase=idle")
            assert status == 200
            prof = json.loads(body)
            assert prof["window_seconds"] == 0.05
            assert prof["phase"] == "idle"
        finally:
            server.stop()

    def test_profilez_404_without_profiler(self):
        server = start_telemetry_server(port=0)
        try:
            status, body = _get(server.url + "/profilez")
            assert status == 404
            assert "sampler" in json.loads(body)["error"]
        finally:
            server.stop()


# ------------------------------------------------- fleet /slo gossip


def _mini_engine(clock, *, bad_frac, tracer=None):
    reg = MetricsRegistry()
    req, bad = reg.counter("req_total"), reg.counter("bad_total")
    store = TimeSeriesStore(registry=reg, clock=clock)
    engine = SLOEngine(
        store,
        [SLO("availability", target=0.9, bad="bad_total",
             total="req_total",
             alerts=(BurnRateAlert("page", burn_rate_threshold=5.0,
                                   long_window_seconds=4.0,
                                   short_window_seconds=1.0),),
             budget_window_seconds=60.0)],
        registry=reg, tracer=tracer, clock=clock)
    for _ in range(8):
        clock.advance(0.5)
        req.inc(10)
        bad.inc(int(10 * bad_frac))
        store.scrape_once()
        engine.evaluate()
    return engine


class TestFleetSLOGossip:
    def test_publish_collect_merge_round_trip(self):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.observability.slo_gossip import (
            SLOStatusPublisher, collect_fleet_slo, collect_slo_statuses)

        healthy = _mini_engine(_ManualClock(), bad_frac=0.0)
        burning = _mini_engine(_ManualClock(), bad_frac=1.0)
        store = TCPStore(is_master=True, world_size=1)
        SLOStatusPublisher(healthy, 0, store).publish()
        SLOStatusPublisher(burning, 1, store).publish()
        statuses = collect_slo_statuses(store, [0, 1, 2])   # 2 absent
        assert [src for src, _ in statuses] == ["replica0", "replica1"]

        fleet = collect_fleet_slo(store, [0, 1])
        assert fleet["fleet"] is True
        assert fleet["page_active"] is True         # OR over replicas
        assert fleet["replicas"]["replica0"]["page_active"] is False
        assert fleet["replicas"]["replica1"]["page_active"] is True
        obj = fleet["slos"]["availability"]
        assert set(obj["replicas"]) == {"replica0", "replica1"}
        # worst (minimum) remaining budget wins the fleet number
        assert obj["error_budget_ratio"] == \
            obj["replicas"]["replica1"]["error_budget_ratio"]
        assert obj["error_budget_ratio"] < \
            obj["replicas"]["replica0"]["error_budget_ratio"]
        (alert,) = obj["alerts_active"]
        assert alert["replica"] == "replica1"
        assert alert["severity"] == "page"
        # one interleaved timeline, each entry tagged with its replica
        assert all(tr["replica"] == "replica1"
                   for tr in fleet["transitions"])
        assert [tr["time"] for tr in fleet["transitions"]] == \
            sorted(tr["time"] for tr in fleet["transitions"])

    def test_garbled_and_stale_statuses_absent(self):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.observability.slo_gossip import (
            SLOStatusPublisher, collect_slo_statuses)

        store = TCPStore(is_master=True, world_size=1)
        store.set("slo/replica_0", "}{ not json")
        engine = _mini_engine(_ManualClock(), bad_frac=0.0)
        SLOStatusPublisher(engine, 1, store,
                           clock=lambda: 100.0).publish()
        out = collect_slo_statuses(store, [0, 1])
        assert [src for src, _ in out] == ["replica1"]      # 0 garbled
        assert collect_slo_statuses(store, [0, 1], stale_after_s=5.0,
                                    clock=lambda: 200.0) == []
        fresh = collect_slo_statuses(store, [0, 1], stale_after_s=5.0,
                                     clock=lambda: 101.0)
        assert [src for src, _ in fresh] == ["replica1"]

    def test_fleet_endpoint_and_404_without_source(self):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.observability.slo_gossip import (
            SLOStatusPublisher, collect_fleet_slo)

        engine = _mini_engine(_ManualClock(), bad_frac=1.0)
        store = TCPStore(is_master=True, world_size=1)
        SLOStatusPublisher(engine, 0, store).publish()
        server = start_telemetry_server(
            port=0, slo=engine,
            fleet_slo=lambda: collect_fleet_slo(store, [0]))
        try:
            status, body = _get(server.url + "/slo?fleet=1")
            assert status == 200
            fleet = json.loads(body)
            assert fleet["fleet"] is True and fleet["page_active"]
            # plain /slo still serves the local engine
            status, body = _get(server.url + "/slo")
            assert status == 200
            assert "fleet" not in json.loads(body)
        finally:
            server.stop()
        server = start_telemetry_server(port=0, slo=engine)
        try:
            status, body = _get(server.url + "/slo?fleet=1")
            assert status == 404
            assert "fleet" in json.loads(body)["error"]
        finally:
            server.stop()


# ----------------------------------------------------- lint sync-test


class TestSeriesContract:
    def test_profiling_series_stays_in_sync_with_lint_pin(self):
        """tools/analysis pins a copy of the series set (the pass must
        not import the package it analyses) — the sync check both
        comments promise."""
        from tools.analysis.passes import metric_names

        assert tuple(metric_names._PROFILING_SERIES) == \
            tuple(PROFILING_SERIES)


# -------------------------------------------------------- overhead smoke


class TestProfilingOverheadSmoke:
    def test_sampler_walk_under_bound(self):
        """Acceptance: one stack walk over a realistic thread
        population keeps the always-on rate under the documented 1%
        bound (50 ms request model).  Runs in a fresh subprocess: a
        mid-suite interpreter carries daemon threads from earlier test
        modules whose extra stacks inflate every walk — that measures
        the test session, not the sampler."""
        import os
        import subprocess
        import sys

        root = os.path.join(os.path.dirname(__file__), os.pardir)
        code = (
            "import importlib.util, json, sys\n"
            "spec = importlib.util.spec_from_file_location("
            "'bench_mod', sys.argv[1])\n"
            "bench = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(bench)\n"
            "print(json.dumps(bench.bench_profiling()))\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", code,
             os.path.join(root, "bench.py")],
            capture_output=True, text=True, timeout=300, cwd=root,
            env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["implied_request_overhead_ratio"] < \
            out["bound_ratio"], out
        # absolute sanity: sub-millisecond per walk
        assert out["per_sample_us"] < 5000, out
        # all three rates reported (escalated rows are informational)
        assert set(out["rates"]) == {"default", "escalated", "capture"}
