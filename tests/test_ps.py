"""Parameter-server sparse push/pull tests (reference strategy:
test_dist_base.py spawns pserver+trainer subprocesses; here the server
is the in-process native TCPStore master and trainers are threads —
the wire and the atomicity are real)."""
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (PSClient, PSServer, SparseEmbedding,
                                       SparseTable)


@pytest.fixture()
def cluster():
    servers = [PSServer(), PSServer()]
    client = PSClient([s.endpoint for s in servers])
    yield servers, client
    for s in servers:
        s.stop()


class TestSparseTable:
    def test_pull_initializes_deterministically(self, cluster):
        servers, client = cluster
        t = SparseTable(client, "emb", dim=8, init_std=0.1, seed=3)
        a = t.pull([1, 2, 3])
        b = t.pull([1, 2, 3])
        assert a.shape == (3, 8)
        np.testing.assert_array_equal(a, b)   # init is sticky
        c2 = PSClient([s.endpoint for s in servers])
        np.testing.assert_array_equal(
            a, SparseTable(c2, "emb", dim=8, init_std=0.1, seed=3)
            .pull([1, 2, 3]))                 # and shared across clients

    def test_push_accumulates(self, cluster):
        _, client = cluster
        t = SparseTable(client, "t2", dim=4, init_std=0.0)
        base = t.pull([7])
        t.push([7], np.ones((1, 4), np.float32))
        t.push([7], 2 * np.ones((1, 4), np.float32))
        np.testing.assert_allclose(t.pull([7]), base + 3.0, rtol=1e-6)

    def test_rows_shard_across_servers(self, cluster):
        servers, client = cluster
        t = SparseTable(client, "t3", dim=2, init_std=0.0)
        ids = list(range(40))
        t.pull(ids)
        # both store masters should hold some rows
        counts = []
        for s in servers:
            local = PSClient([s.endpoint])._stores[0]
            n = 0
            for rid in ids:
                try:
                    local.get(f"ps/t3/{rid}", blocking=False)
                    n += 1
                except KeyError:
                    pass
            counts.append(n)
        assert sum(counts) == len(ids)
        assert all(c > 0 for c in counts)

    def test_dim_mismatch_is_loud(self, cluster):
        _, client = cluster
        t16 = SparseTable(client, "mix", dim=16, init_std=0.0)
        t16.pull([0])
        t8 = SparseTable(client, "mix", dim=8, init_std=0.0)
        with pytest.raises(ValueError, match="dim"):
            t8.pull([0])          # silent truncation would train garbage
        with pytest.raises(ValueError, match="dim|match"):
            t8.push([0], np.ones((1, 8), np.float32))

    def test_push_first_touch_initializes(self, cluster):
        _, client = cluster
        t = SparseTable(client, "pf", dim=4, init_std=0.05, seed=9)
        t.push([11], np.zeros((1, 4), np.float32))   # push before pull
        # the row got the deterministic init, not zeros
        expected = PSClient._init_row(11, 4, 0.05, 9)
        np.testing.assert_allclose(t.pull([11])[0], expected, rtol=1e-6)

    def test_concurrent_push_is_atomic(self, cluster):
        _, client = cluster
        t = SparseTable(client, "t4", dim=16, init_std=0.0)
        base = t.pull([0]).copy()
        n_threads, n_pushes = 4, 25

        endpoints = [f"{st.host}:{st.port}" for st in client._stores]

        def worker():
            tt = SparseTable(PSClient(endpoints), "t4", dim=16,
                             init_std=0.0)
            for _ in range(n_pushes):
                tt.push([0], np.ones((1, 16), np.float32))

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        np.testing.assert_allclose(
            t.pull([0]), base + n_threads * n_pushes, rtol=1e-5)


class TestSparseEmbedding:
    def test_async_sgd_round_trip(self, cluster):
        _, client = cluster
        emb = SparseEmbedding(
            SparseTable(client, "e", dim=4, init_std=0.0), lr=0.5)
        rows = np.asarray(emb.forward([5, 9]))
        grad = np.ones((2, 4), np.float32)
        emb.apply_grads(grad)
        np.testing.assert_allclose(
            np.asarray(emb.forward([5, 9])), rows - 0.5, rtol=1e-6)


class TestInitRows:
    """The vectorized deterministic initializer (splitmix64 + Box-Muller
    with XOR-separated streams)."""

    def test_negative_ids_ok(self):
        rows = PSClient._init_rows([-5, 3, -(2**40)], 8, 0.01, 0)
        assert rows.shape == (3, 8)
        assert np.isfinite(rows).all()

    def test_padding_row_not_extreme(self):
        """(rid=0, col=0, seed=0) must not hit the splitmix 0->0 fixed
        point: every element stays within a sane sigma range."""
        rows = PSClient._init_rows([0], 64, 1.0, 0)
        assert np.abs(rows).max() < 6.0

    def test_adjacent_rows_independent(self):
        """Stream separation: row r's uniforms must not alias row r+1's
        (additive tweaks did: mix(base + C1) IS the next row)."""
        rows = PSClient._init_rows(list(range(512)), 32, 1.0, 0)
        a, b = rows[:-1].ravel(), rows[1:].ravel()
        corr = np.corrcoef(a, b)[0, 1]
        assert abs(corr) < 0.05, corr
        # and the distribution is roughly standard normal
        assert abs(rows.mean()) < 0.02 and abs(rows.std() - 1.0) < 0.02

    def test_single_row_matches_batch(self):
        one = PSClient._init_row(7, 16, 0.05, 3)
        batch = PSClient._init_rows([5, 7, 9], 16, 0.05, 3)
        np.testing.assert_array_equal(one, batch[1])
