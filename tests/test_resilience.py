"""Resilience subsystem tests: atomic writes, deterministic fault
injection, crash-safe checkpointing (kill at EVERY injected boundary),
retry/backoff, TCPStore reconnection, and killed-and-resumed Model.fit
reproducing the uninterrupted loss curve.
"""
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Callback, CheckpointCallback, Model
from paddle_tpu.io import Dataset
from paddle_tpu.resilience import (CheckpointManager, Deadline,
                                   FaultInjector, FaultSpec, RetryError,
                                   SimulatedCrash, atomic_write,
                                   backoff_delays, fault_point,
                                   injected_faults, install_from_env,
                                   retry, uninstall, verify_checkpoint)


def _state(scale):
    """A deterministic pytree; every leaf is a function of ``scale`` so a
    restored checkpoint's provenance is readable off its values."""
    return {"w": np.arange(16.0).reshape(4, 4) * scale,
            "nested": {"b": np.full((3,), float(scale))},
            "step_marker": np.asarray([scale], np.int64)}


def _assert_state(tree, scale):
    ref = _state(scale)
    np.testing.assert_array_equal(tree["w"], ref["w"])
    np.testing.assert_array_equal(tree["nested/b"], ref["nested"]["b"])
    np.testing.assert_array_equal(tree["step_marker"], ref["step_marker"])


# ------------------------------------------------------------ atomic IO


class TestAtomicWrite:
    def test_commit_and_crc(self, tmp_path):
        p = tmp_path / "f.bin"
        with atomic_write(str(p), "wb") as f:
            f.write(b"hello ")
            f.write(b"world")
        assert p.read_bytes() == b"hello world"
        import zlib

        with atomic_write(str(p), "wb") as f:
            f.write(b"checksummed")
            crc = f.crc32
        assert crc == zlib.crc32(b"checksummed")

    def test_failure_leaves_target_untouched(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"old")
        with pytest.raises(RuntimeError):
            with atomic_write(str(p), "wb") as f:
                f.write(b"new-partial")
                raise RuntimeError("writer died")
        assert p.read_bytes() == b"old"
        # ordinary failures clean their tmp file up
        assert list(tmp_path.iterdir()) == [p]

    def test_append_mode_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="append"):
            with atomic_write(str(tmp_path / "f"), "ab"):
                pass


@pytest.mark.faultinject
class TestFaultInjector:
    def test_fires_only_at_matching_occurrence(self):
        inj = FaultInjector([FaultSpec("site.a", "kill", occurrence=3)])
        try:
            paddle.resilience.install(inj)
            fault_point("site.a")
            fault_point("site.a")
            fault_point("site.b")          # different site: never fires
            with pytest.raises(SimulatedCrash) as ei:
                fault_point("site.a")
            assert ei.value.occurrence == 3
            assert inj.hits("site.a") == 3
            assert inj.fired == [("site.a", "kill", 3)]
        finally:
            uninstall()

    def test_io_error_is_catchable_kill_is_not(self):
        with injected_faults(FaultSpec("s", "io_error")):
            with pytest.raises(OSError):
                fault_point("s")
        # a simulated SIGKILL must not be swallowable by the generic
        # recovery idiom — it is deliberately not an Exception
        assert not issubclass(SimulatedCrash, Exception)
        with injected_faults(FaultSpec("s", "kill")):
            with pytest.raises(SimulatedCrash):
                fault_point("s")

    def test_torn_write_truncates_deterministically(self, tmp_path):
        sizes = []
        for _ in range(2):
            p = tmp_path / "t.bin"
            p.write_bytes(bytes(1000))
            with injected_faults(FaultSpec("s", "torn_write"), seed=7):
                with pytest.raises(SimulatedCrash):
                    fault_point("s", path=str(p))
            sizes.append(p.stat().st_size)
        assert sizes[0] == sizes[1]        # same seed → same torn length
        assert 0 < sizes[0] < 1000

    def test_stall_sleeps_and_counts(self):
        from paddle_tpu.observability import default_registry

        fam = default_registry().get("faults_injected_total")
        before = fam.labels(site="s2", kind="stall").value if fam else 0
        t0 = time.perf_counter()
        with injected_faults(FaultSpec("s2", "stall", stall_s=0.05)):
            fault_point("s2")
        assert time.perf_counter() - t0 >= 0.045
        fam = default_registry().get("faults_injected_total")
        assert fam.labels(site="s2", kind="stall").value == before + 1

    def test_env_gated_install(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FAULTS", "x.y:io_error:2")
        inj = install_from_env()
        try:
            assert inj is not None
            fault_point("x.y")
            with pytest.raises(OSError):
                fault_point("x.y")
        finally:
            uninstall()
        monkeypatch.delenv("PADDLE_TPU_FAULTS")
        assert install_from_env() is None


# ---------------------------------------------------------------- retry


class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = []

        @retry(max_attempts=5, base=1e-4, cap=1e-3)
        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return 42

        assert flaky() == 42
        assert len(calls) == 3

    def test_exhaustion_raises_retry_error_chaining_last(self):
        @retry(max_attempts=3, base=1e-4, cap=1e-3)
        def doomed():
            raise TimeoutError("always")

        with pytest.raises(RetryError) as ei:
            doomed()
        assert ei.value.attempts == 3
        assert isinstance(ei.value.last, TimeoutError)

    def test_non_retryable_exception_passes_through(self):
        @retry(exceptions=(OSError,), max_attempts=5)
        def typed():
            raise ValueError("not retryable")

        with pytest.raises(ValueError):
            typed()

    def test_deadline(self):
        dl = Deadline(0.05)
        assert not dl.expired()
        assert dl.remaining() <= 0.05
        dl.sleep(1.0)                      # clamped to the deadline
        assert dl.expired() and dl.remaining() == 0.0
        assert not Deadline(None).expired()

    def test_backoff_delays_capped_and_jittered(self):
        ds = backoff_delays(base=0.01, factor=2.0, cap=0.04, jitter=False)
        assert [next(ds) for _ in range(5)] == \
            [0.01, 0.02, 0.04, 0.04, 0.04]
        import random

        rng = random.Random(0)
        ds = backoff_delays(base=0.01, cap=0.04, jitter=True, rng=rng)
        vals = [next(ds) for _ in range(8)]
        assert all(0.0 <= v <= 0.04 for v in vals)


# ----------------------------------------------- crash-safe checkpoints


class TestCheckpointManager:
    def test_roundtrip_and_latest(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.latest() is None
        with pytest.raises(FileNotFoundError):
            mgr.restore()
        mgr.save(_state(1), step=1)
        mgr.save(_state(2), step=2)
        assert mgr.steps() == [1, 2] and mgr.latest() == 2
        step, tree, manifest = mgr.restore()
        assert step == 2 and manifest["step"] == 2
        _assert_state(tree, 2)
        # pinned restore of an older step
        step, tree, _ = mgr.restore(step=1)
        assert step == 1
        _assert_state(tree, 1)

    def test_resave_of_committed_step_supersedes(self, tmp_path):
        """After a fallback restore (or an async save racing a crash) a
        trainer legitimately re-reaches a step that already exists on
        disk; the re-save must replace it, not ENOTEMPTY."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_state(1), step=1)
        mgr.save(_state(2), step=2)
        mgr.save(_state(7), step=2)        # same step, new bytes
        step, tree, _ = mgr.restore()
        assert step == 2
        _assert_state(tree, 7)

    def test_retention_gc(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
        for i in (1, 2, 3, 4):
            mgr.save(_state(i), step=i)
        assert mgr.steps() == [3, 4]

    def test_async_save(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(_state(1), step=1)
        mgr.wait()
        assert mgr.latest() == 1
        step, tree, _ = mgr.restore()
        _assert_state(tree, 1)

    def test_async_save_snapshots_before_handoff(self, tmp_path):
        """The device→host snapshot must be a deep copy taken before
        the background thread starts: a trainer mutating (or donating)
        its live tree immediately after save() returns must not be able
        to tear the bytes being written.  jax.device_get alone passes
        host numpy leaves through BY REFERENCE — this is the race."""
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        # stall the background write so the mutation below happens
        # while the save is provably still in flight
        live = _state(1)
        with injected_faults(FaultSpec("checkpoint.before_shard",
                                       "stall", stall_s=0.2)):
            mgr.save(live, step=1)
            live["w"][:] = -777.0          # the next "train step"
            live["nested"]["b"][:] = -777.0
            mgr.wait()
        step, tree, _ = mgr.restore()
        assert step == 1
        _assert_state(tree, 1)             # pre-mutation values

    def test_restore_before_step_skips_newer(self, tmp_path):
        """before_step bounds the fallback walk: the rollback path must
        never restore the anomalous step's own (poisoned) save."""
        mgr = CheckpointManager(str(tmp_path))
        for i in (1, 2, 3):
            mgr.save(_state(i), step=i)
        step, tree, _ = mgr.restore(before_step=3)
        assert step == 2
        _assert_state(tree, 2)
        with pytest.raises(FileNotFoundError):
            mgr.restore(before_step=1)

    def test_corrupt_committed_checkpoint_falls_back(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_state(1), step=1)
        mgr.save(_state(2), step=2)
        # bit-rot one shard of the newest checkpoint
        p2 = mgr.step_path(2)
        victim = next(os.path.join(r, f) for r, _, fs in os.walk(p2)
                      for f in sorted(fs) if f.endswith(".npy"))
        with open(victim, "r+b") as f:
            f.seek(80)
            f.write(b"\xff\xff\xff\xff")
        ok, errors = verify_checkpoint(p2)
        assert not ok and "crc32" in errors[0]
        assert mgr.latest() == 1           # discovery skips corrupt
        step, tree, _ = mgr.restore()      # restore falls back
        assert step == 1
        _assert_state(tree, 1)
        with pytest.raises(ValueError, match="verification"):
            mgr.restore(step=2)            # pinned: fail loudly


@pytest.mark.faultinject
class TestCrashConsistency:
    """Kill the saver at every injected boundary: recovery must always
    find the previous committed step, bitwise intact."""

    KILL_POINTS = [
        ("checkpoint.before_shard", 1),     # before any shard bytes
        ("checkpoint.before_shard", 3),     # between shards
        ("checkpoint.shard_write", 1),      # first shard committed-ish
        ("checkpoint.shard_write", 2),      # mid shard sequence
        ("checkpoint.before_manifest", 1),  # all shards, no manifest
        ("checkpoint.manifest_write", 1),   # manifest bytes on disk,
                                            # not yet renamed
        ("checkpoint.before_commit", 1),    # dir complete, not renamed
    ]

    def test_kill_after_commit_keeps_new_step(self, tmp_path):
        """The rename IS the commit: a kill one instruction later
        (checkpoint.after_commit) must find the NEW step restorable."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_state(1), step=1)
        with injected_faults(FaultSpec("checkpoint.after_commit",
                                       "kill")):
            with pytest.raises(SimulatedCrash):
                mgr.save(_state(2), step=2)
        assert CheckpointManager(str(tmp_path)).latest() == 2
        step, tree, _ = mgr.restore()
        assert step == 2
        _assert_state(tree, 2)

    def test_kill_mid_model_save_keeps_old_blob(self, tmp_path):
        """hapi Model.save writes through atomic_write(site=
        'hapi.model_save'): a kill mid-write leaves the previous
        .pdparams intact."""
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model

        paddle.seed(0)
        model = Model(nn.Linear(4, 2))
        path = str(tmp_path / "m")
        model.save(path)
        import pickle

        with open(path + ".pdparams", "rb") as f:
            before = pickle.load(f)
        with injected_faults(FaultSpec("hapi.model_save", "kill")):
            with pytest.raises(SimulatedCrash):
                model.save(path)
        with open(path + ".pdparams", "rb") as f:
            after = pickle.load(f)
        for k, v in before["params"].items():
            np.testing.assert_array_equal(after["params"][k], v)

    def test_killed_save_tmp_dir_swept_on_init(self, tmp_path):
        """A step_N.tmp left by a kill-mid-save must be reclaimed by the
        next manager construction (the relaunch path) — orphaned tmp
        dirs must not accumulate across preemptions."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_state(1), step=1)
        with injected_faults(FaultSpec("checkpoint.before_manifest",
                                       "kill")):
            with pytest.raises(SimulatedCrash):
                mgr.save(_state(2), step=2)
        leftovers = [n for n in os.listdir(str(tmp_path))
                     if n.endswith(".tmp")]
        assert leftovers == ["step_0000000002.tmp"]
        # a fresh manager (what a relaunched trainer constructs) sweeps
        mgr2 = CheckpointManager(str(tmp_path))
        assert not [n for n in os.listdir(str(tmp_path))
                    if n.endswith(".tmp")]
        assert mgr2.latest() == 1          # committed step untouched
        # read-side managers can opt out (a live trainer may be writing)
        with injected_faults(FaultSpec("checkpoint.before_commit",
                                       "kill")):
            with pytest.raises(SimulatedCrash):
                mgr2.save(_state(3), step=3)
        CheckpointManager(str(tmp_path), sweep_orphans=False)
        assert [n for n in os.listdir(str(tmp_path))
                if n.endswith(".tmp")] == ["step_0000000003.tmp"]

    @pytest.mark.parametrize("site,occurrence", KILL_POINTS)
    def test_kill_point_recovers_previous_step(self, tmp_path, site,
                                               occurrence):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_state(1), step=1)
        with injected_faults(FaultSpec(site, "kill",
                                       occurrence=occurrence)):
            with pytest.raises(SimulatedCrash):
                mgr.save(_state(2), step=2)
        assert mgr.latest() == 1
        step, tree, _ = mgr.restore()
        assert step == 1
        _assert_state(tree, 1)
        # the interrupted save's debris must not block the next save
        mgr.save(_state(2), step=2)
        step, tree, _ = mgr.restore()
        assert step == 2
        _assert_state(tree, 2)

    def test_torn_shard_write_never_commits(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_state(1), step=1)
        with injected_faults(FaultSpec("checkpoint.shard_write",
                                       "torn_write", occurrence=2)):
            with pytest.raises(SimulatedCrash):
                mgr.save(_state(2), step=2)
        assert mgr.latest() == 1
        step, tree, _ = mgr.restore()
        assert step == 1
        _assert_state(tree, 1)

    def test_transient_io_error_then_clean_retry(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        with injected_faults(FaultSpec("checkpoint.shard_write",
                                       "io_error", occurrence=1)):
            with pytest.raises(OSError):
                mgr.save(_state(1), step=1)
            mgr.save(_state(1), step=1)    # same injector: occurrence
        step, tree, _ = mgr.restore()      # 1 already consumed
        assert step == 1
        _assert_state(tree, 1)

    def test_framework_io_save_crash_keeps_old_blob(self, tmp_path):
        path = str(tmp_path / "blob.pdparams")
        paddle.save({"a": np.ones(4)}, path)
        with injected_faults(FaultSpec("framework_io.save", "kill")):
            with pytest.raises(SimulatedCrash):
                paddle.save({"a": np.zeros(4)}, path)
        out = paddle.load(path, return_numpy=True)
        np.testing.assert_array_equal(out["a"], np.ones(4))


# ------------------------------------------------ TCPStore retry/backoff


class TestStoreBackoff:
    def test_connect_retries_until_master_appears(self):
        """Client dials BEFORE the master binds — rendezvous-order
        robustness that a single connect attempt cannot provide."""
        from paddle_tpu.distributed.store import TCPStore

        # reserve a port, release it, then bind the master there late
        probe = TCPStore(is_master=True, world_size=1)
        port = probe.port
        del probe
        holder = {}

        def late_master():
            time.sleep(0.4)
            holder["master"] = TCPStore(port=port, is_master=True,
                                        world_size=2)

        t = threading.Thread(target=late_master, daemon=True)
        t.start()
        client = TCPStore(port=port, is_master=False, world_size=2,
                          timeout=15.0)
        t.join()
        holder["master"].set("k", b"v")
        assert client.get("k", timeout=5) == b"v"

    def test_connect_timeout_still_raises(self):
        from paddle_tpu.distributed.store import TCPStore

        t0 = time.perf_counter()
        with pytest.raises(TimeoutError):
            TCPStore(host="127.0.0.1", port=1, is_master=False,
                     timeout=0.5)
        assert time.perf_counter() - t0 < 10.0

    def test_blocking_get_backs_off_but_stays_responsive(self):
        from paddle_tpu.distributed.store import TCPStore

        master = TCPStore(is_master=True, world_size=1)

        def late_set():
            time.sleep(0.3)
            master.set("late", b"x")

        threading.Thread(target=late_set, daemon=True).start()
        t0 = time.perf_counter()
        assert master.get("late", blocking=True, timeout=10) == b"x"
        # exponential backoff caps at 100ms: arrival latency stays small
        assert time.perf_counter() - t0 < 2.0


# --------------------------------------- killed + resumed training run


class _Toy(Dataset):
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.y = rng.randint(0, 2, (n,)).astype(np.int64)
        self.x = (rng.randn(n, 8) * 0.3 +
                  self.y[:, None].astype(np.float32) * 2.0
                  ).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class _LossRecorder(Callback):
    def __init__(self):
        super().__init__()
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(logs["loss"])


def _fit_model(seed=3, lr=0.1):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = Model(net)
    opt = paddle.optimizer.Momentum(learning_rate=lr,
                                    parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    return model


@pytest.mark.faultinject
class TestFitAutoResume:
    def test_killed_run_resumes_with_matching_loss_curve(self, tmp_path):
        """2 epochs × 4 steps; kill at global step 6 (mid-epoch 2);
        relaunch with resume_from → the combined loss trajectory equals
        the uninterrupted run's, step for step."""
        ref = _LossRecorder()
        _fit_model().fit(_Toy(), batch_size=16, epochs=2, shuffle=False,
                         verbose=0, callbacks=[ref])
        assert len(ref.losses) == 8

        ckdir = str(tmp_path / "ck")
        part_a = _LossRecorder()
        with injected_faults(FaultSpec("hapi.train_step", "kill",
                                       occurrence=6)):
            with pytest.raises(SimulatedCrash):
                _fit_model().fit(
                    _Toy(), batch_size=16, epochs=2, shuffle=False,
                    verbose=0,
                    callbacks=[part_a,
                               CheckpointCallback(ckdir, every_n_steps=1)])
        assert len(part_a.losses) == 6

        # relaunch from scratch: DIFFERENT seed — restore must overwrite
        part_b = _LossRecorder()
        _fit_model(seed=99).fit(
            _Toy(), batch_size=16, epochs=2, shuffle=False, verbose=0,
            callbacks=[part_b, CheckpointCallback(ckdir, every_n_steps=1)],
            resume_from=ckdir)
        assert len(part_b.losses) == 2
        np.testing.assert_allclose(part_a.losses + part_b.losses,
                                   ref.losses, rtol=1e-5, atol=1e-6)

    def test_resume_from_empty_dir_is_fresh_start(self, tmp_path):
        hist = _fit_model().fit(_Toy(), batch_size=16, epochs=1,
                                shuffle=False, verbose=0,
                                resume_from=str(tmp_path / "none"))
        assert len(hist) == 1

    def test_resume_restores_lr_scheduler_state(self, tmp_path):
        """A stateful LR scheduler (its own step counter) rides in the
        checkpoint: the resumed run's per-step LR sequence continues the
        uninterrupted run's exactly — not one notch off."""
        from paddle_tpu.hapi.callbacks import LRScheduler as LRStepCB
        from paddle_tpu.optimizer.lr import StepDecay

        def sched_model(seed=3):
            paddle.seed(seed)
            net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                nn.Linear(16, 2))
            model = Model(net)
            # halve every 2 scheduler steps: any off-by-one in the
            # restored counter shifts the whole remaining LR sequence
            sched = StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
            opt = paddle.optimizer.Momentum(learning_rate=sched,
                                            parameters=model.parameters())
            model.prepare(opt, nn.CrossEntropyLoss())
            return model, sched

        class LRRecorder(Callback):
            def __init__(self):
                super().__init__()
                self.lrs = []

            def on_train_batch_end(self, step, logs=None):
                # first in the callback list: records the LR this batch
                # actually trained with, before the scheduler advances
                self.lrs.append(self.model._optimizer.get_lr())

        def run(model, sched, ckdir=None, resume=None):
            rec = LRRecorder()
            cbs = [rec, LRStepCB(by_step=True)]
            if ckdir:
                # scheduler steps BEFORE the checkpoint callback saves,
                # so the saved counter matches "batches completed"
                cbs.append(CheckpointCallback(ckdir, every_n_steps=1))
            model.fit(_Toy(), batch_size=16, epochs=2, shuffle=False,
                      verbose=0, callbacks=cbs, resume_from=resume)
            return rec.lrs

        ref_lrs = run(*sched_model())
        assert len(ref_lrs) == 8
        assert len(set(ref_lrs)) > 2         # the schedule actually moves

        ckdir = str(tmp_path / "ck")
        model_a, sched_a = sched_model()
        with injected_faults(FaultSpec("hapi.train_step", "kill",
                                       occurrence=6)):
            with pytest.raises(SimulatedCrash):
                run(model_a, sched_a, ckdir=ckdir)

        # fresh scheduler (counter at 0) — restore must fast-forward it
        model_b, sched_b = sched_model(seed=99)
        lrs_b = run(model_b, sched_b, ckdir=ckdir, resume=ckdir)
        assert sched_b.last_epoch == 8       # 6 before kill + 2 after
        np.testing.assert_allclose(ref_lrs[6:], lrs_b, rtol=0, atol=0)

    def test_resume_restores_rng_streams(self, tmp_path):
        """The checkpoint carries the stateful RNG: a resumed run's draws
        continue the killed run's sequence, not a fresh seed's."""
        import jax

        from paddle_tpu.core.random import split_key

        mgr = CheckpointManager(str(tmp_path))
        model = _fit_model()               # layer init draws; seed after

        paddle.seed(7)
        _ = [split_key() for _ in range(3)]
        expected = jax.random.key_data(split_key())   # the 4th draw

        paddle.seed(7)
        _ = [split_key() for _ in range(3)]
        from paddle_tpu.hapi.callbacks import (_pack_fit_state,
                                               restore_fit_state)

        tree, counters = _pack_fit_state(model)
        mgr.save(tree, step=1, extra={"rng_counters": counters,
                                      "epoch": 0, "next_step": 0,
                                      "global_step": 1})
        paddle.seed(12345)                   # clobber the stream
        _ = [split_key() for _ in range(9)]
        info = restore_fit_state(model, mgr)
        assert info["global_step"] == 1
        np.testing.assert_array_equal(jax.random.key_data(split_key()),
                                      expected)


# ---------------------------------------------------- fault-sites lint


def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(__file__), os.pardir,
                           "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestFaultSitesLint:
    # the repo-wide sweep now runs ONCE in the consolidated suite:
    # tests/test_static_analysis.py::TestTier1Suite

    def test_known_sites_collected(self):
        mod = _load_tool("check_fault_sites")
        sites = mod.collect_sites()
        # positional fault_point literals AND site= keyword literals
        for expected in ("hapi.train_step", "checkpoint.before_commit",
                         "checkpoint.shard_write", "supervisor.spawn",
                         "supervisor.rendezvous", "framework_io.save"):
            assert expected in sites, expected
        # a keyword DEFAULT is not a registered site
        assert "io.write" not in sites

    def test_lint_catches_an_uncovered_site(self, tmp_path):
        mod = _load_tool("check_fault_sites")
        pkg = tmp_path / "pkg"
        tests = tmp_path / "tests"
        pkg.mkdir()
        tests.mkdir()
        (pkg / "thing.py").write_text(
            "from x import fault_point, atomic_write\n"
            "def f(p):\n"
            "    fault_point('thing.covered')\n"
            "    fault_point('thing.naked')\n"
            "    with atomic_write(p, site='thing.kw') as fh:\n"
            "        fh.write(b'x')\n")
        (tests / "test_thing.py").write_text(
            "SPEC = 'thing.covered:kill:1,thing.kw:io_error'\n")
        out = mod.check(root=str(pkg), tests_root=str(tests))
        assert len(out) == 1 and out[0].startswith("thing.naked ")


# --------------------------------------------------- atomic-writes lint


class TestAtomicWritesLint:
    # the repo-wide sweep now runs ONCE in the consolidated suite:
    # tests/test_static_analysis.py::TestTier1Suite

    def test_lint_catches_a_planted_violation(self, tmp_path):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_atomic_writes",
            os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                         "check_atomic_writes.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        bad = tmp_path / "pkg" / "writer.py"
        bad.parent.mkdir()
        bad.write_text('def f(p):\n    with open(p, "wb") as fh:\n'
                       '        fh.write(b"x")\n')
        (tmp_path / "pkg" / "reader.py").write_text(
            'def g(p):\n    return open(p).read()\n')
        out = mod.check(root=str(tmp_path / "pkg"))
        assert len(out) == 1 and "writer.py:2" in out[0]
