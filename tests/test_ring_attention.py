"""Ring attention tests (SURVEY §5.7: the new-capability requirement).

All on the virtual CPU mesh; pallas kernels run in interpret mode there.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.kernels.ring_attention import ring_attention
from paddle_tpu.ops.attention import _naive_attention


def _qkv(B, H, S, D, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(B, H, S, D).astype(np.float32) * 0.5
    return mk(), mk(), mk()


def _ring_run(q, k, v, sep, grad=False):
    """shard_map ring over 'sep' with the sequence split in rank order."""
    mesh = Mesh(np.array(jax.devices()[:sep]), ("sep",))

    def local(q, k, v):
        return ring_attention(q, k, v, "sep", causal=True)

    spec = P(None, None, "sep", None)
    mapped = jax.shard_map(local, mesh=mesh, in_specs=(spec,) * 3,
                           out_specs=spec, check_vma=True)
    if not grad:
        return jax.jit(mapped)(q, k, v)

    def loss(q, k, v):
        return (mapped(q, k, v).astype(jnp.float32) ** 2).sum()

    return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)


def _ref_run(q, k, v, grad=False):
    ref = lambda q, k, v: _naive_attention(jnp.asarray(q), jnp.asarray(k),
                                           jnp.asarray(v), causal=True,
                                           training=False)
    if not grad:
        return ref(q, k, v)

    def loss(q, k, v):
        return (ref(q, k, v).astype(jnp.float32) ** 2).sum()

    return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))


class TestRingParity:
    def test_fwd_matches_naive_sep4(self):
        q, k, v = _qkv(2, 2, 512, 64)
        out = _ring_run(q, k, v, sep=4)
        ref = _ref_run(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3)

    def test_fwd_matches_sep2(self):
        q, k, v = _qkv(1, 2, 256, 64, seed=3)
        out = _ring_run(q, k, v, sep=2)
        ref = _ref_run(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3)

    def test_grads_match_naive(self):
        q, k, v = _qkv(1, 2, 256, 64, seed=5)
        dq, dk, dv = _ring_run(q, k, v, sep=2, grad=True)
        rq, rk, rv = _ref_run(q, k, v, grad=True)
        for a, b, name in ((dq, rq, "dq"), (dk, rk, "dk"), (dv, rv, "dv")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-2, rtol=1e-2, err_msg=name)

    def test_seq4096_parity(self):
        """VERDICT r2 #6 'done' criterion: seq 4096, sep=4, interpret mode."""
        q, k, v = _qkv(1, 1, 4096, 64, seed=7)
        out = _ring_run(q, k, v, sep=4)
        ref = _ref_run(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3)

    def test_s_local_tile_check(self):
        q, k, v = _qkv(1, 1, 256, 64)
        mesh = Mesh(np.array(jax.devices()[:2]), ("sep",))
        with pytest.raises(ValueError, match="128"):
            jax.shard_map(
                lambda q, k, v: ring_attention(q[:, :, :100], k[:, :, :100],
                                               v[:, :, :100], "sep"),
                mesh=mesh, in_specs=(P(None, None, "sep", None),) * 3,
                out_specs=P(None, None, "sep", None), check_vma=True,
            )(q, k, v)


@pytest.mark.slow
class TestEngineRing:
    """sep=4 ring beats the Ulysses head cap: num_heads=2 < sep=4."""

    def test_ring_lifts_head_cap_and_matches(self):
        from paddle_tpu.distributed.engine import EngineConfig, HybridEngine
        from paddle_tpu.models.gpt import GPTConfig

        cfg = dict(vocab_size=256, max_seq_len=512, hidden=128,
                   num_layers=2, num_heads=2, ffn_hidden=256,
                   dtype="float32", use_flash=False, remat="nothing")
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, 256, (2, 512)).astype(np.int32)
        labels = np.concatenate([tokens[:, 1:], np.full((2, 1), -100)],
                                axis=1).astype(np.int32)

        base = HybridEngine(GPTConfig(**cfg), devices=jax.devices()[:1])
        bp, bo = base.init(seed=0)
        base_losses = []
        for _ in range(2):
            bp, bo, l = base.step(bp, bo, tokens, labels, lr=1e-3)
            base_losses.append(float(l))

        # Ulysses would assert here: heads(2) % sep(4) != 0
        ring = HybridEngine(GPTConfig(**cfg, seq_parallel="ring"), sep=4,
                            devices=jax.devices()[:4])
        rp, ro = ring.init(seed=0)
        ring_losses = []
        for _ in range(2):
            rp, ro, l = ring.step(rp, ro, tokens, labels, lr=1e-3)
            ring_losses.append(float(l))
        np.testing.assert_allclose(ring_losses, base_losses, atol=5e-4,
                                   rtol=1e-4)

    def test_ulysses_head_cap_still_asserts(self):
        from paddle_tpu.distributed.engine import HybridEngine
        from paddle_tpu.models.gpt import GPTConfig

        cfg = GPTConfig(vocab_size=256, max_seq_len=512, hidden=128,
                        num_layers=2, num_heads=2, ffn_hidden=256,
                        dtype="float32")
        with pytest.raises(AssertionError, match="ring"):
            HybridEngine(cfg, sep=4, devices=jax.devices()[:4])
