"""Serving subsystem tests: paged KV cache, paged-attention decode,
continuous batching, sampling determinism — plus regression tests for
the roi_align edge-semantics and Conll05 parse-guard fixes that rode in
the same PR."""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.paged_attention import (_paged_attention_kernel,
                                                _paged_attention_ref,
                                                _ragged_attention_kernel,
                                                _ragged_attention_ref,
                                                paged_attention,
                                                paged_attention_available)
from paddle_tpu.models.gpt import GPT_CONFIGS, gpt_forward, gpt_init
from paddle_tpu.serving import (Engine, PagedKVCache, RequestState,
                                SamplingParams)


def _tiny_cfg():
    # fp32 everywhere: the greedy-parity tests compare argmax across two
    # computation orders, so bf16 rounding noise is not welcome
    return dataclasses.replace(GPT_CONFIGS["tiny"], dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = gpt_init(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


# one stable jitted forward per config: an EAGER gpt_forward builds a
# fresh scan closure (fresh jaxpr) per call, so every oracle step would
# compile a brand-new executable — churning jax's bounded eager cache
# and the process mmap budget across a long suite.  With a stable jit
# identity each [1, L] compiles exactly once per process.
_ORACLE_FWD = {}


def _oracle_forward(cfg):
    fn = _ORACLE_FWD.get(id(cfg))
    if fn is None:
        fn = _ORACLE_FWD.setdefault(
            id(cfg), jax.jit(lambda p, t: gpt_forward(cfg, p, t)))
    return fn


def naive_generate(cfg, params, prompt, n_new):
    """Full-recompute greedy decoding — the correctness oracle."""
    fwd = _oracle_forward(cfg)
    toks = list(prompt)
    for _ in range(n_new):
        logits = fwd(params, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


# ------------------------------------------------------------- page pool


class TestPagedKVCache:
    def _cache(self, num_pages=8, page_size=4):
        return PagedKVCache(num_layers=2, num_heads=2, head_dim=4,
                            num_pages=num_pages, page_size=page_size,
                            max_seq_len=32)

    def test_alloc_free_reuse(self):
        c = self._cache()
        assert c.allocate("a", 9)            # 3 pages
        assert c.num_used_pages == 3
        table = c.page_table("a")
        assert len(table) == c.max_pages_per_seq
        assert len(set(table[:3])) == 3
        c.free("a")
        assert c.num_free_pages == 8
        # freed pages are reusable immediately
        assert c.allocate("b", 32)           # all 8 pages
        assert c.num_free_pages == 0
        c.free("b")

    def test_exhaustion_returns_false_without_partial_alloc(self):
        c = self._cache()
        assert c.allocate("a", 20)           # 5 of 8 pages
        free_before = c.num_free_pages
        assert not c.allocate("b", 16)       # needs 4, only 3 left
        assert c.num_free_pages == free_before   # nothing leaked
        assert c.extend("a", 32)             # grow to all 8
        assert not c.extend("a", 33) if c.max_pages_per_seq > 8 else True

    def test_occupancy_and_extend(self):
        c = self._cache()
        c.allocate("a", 4)
        assert c.occupancy() == pytest.approx(1 / 8)
        assert c.extend("a", 5)              # second page
        assert c.occupancy() == pytest.approx(2 / 8)
        assert c.extend("a", 5)              # idempotent: already covered
        assert c.occupancy() == pytest.approx(2 / 8)

    def test_defrag_compacts_and_preserves_contents(self):
        c = self._cache()
        c.allocate("a", 8)
        c.allocate("b", 8)
        c.allocate("c", 8)
        # stamp each sequence's pages with a recognizable value
        for sid, val in (("a", 1.0), ("b", 2.0), ("c", 3.0)):
            for p in c.page_table(sid)[:2]:
                c.k_pages = c.k_pages.at[:, p].set(val)
        c.free("b")                          # hole in the middle
        before = {sid: np.asarray(c.k_pages[0, c.page_table(sid)[:2]])
                  for sid in ("a", "c")}
        moved = c.defrag()
        assert moved > 0
        # live pages now occupy the low-index prefix
        live = sorted(p for sid in ("a", "c") for p in c.page_table(sid)[:2])
        assert live == list(range(4))
        for sid in ("a", "c"):
            after = np.asarray(c.k_pages[0, c.page_table(sid)[:2]])
            np.testing.assert_array_equal(before[sid], after)
        assert c.defrag() == 0               # already compact


# ----------------------------------------------------- paged attention


class TestPagedAttention:
    def _case(self, dtype=jnp.float32):
        B, H, hd, P, ps, M = 3, 4, 16, 12, 4, 4
        ks = jax.random.split(jax.random.key(1), 3)
        q = jax.random.normal(ks[0], (B, H, hd), dtype)
        kp = jax.random.normal(ks[1], (P, ps, H, hd), dtype)
        vp = jax.random.normal(ks[2], (P, ps, H, hd), dtype)
        tables = jnp.asarray([[3, 1, 7, 2], [5, 8, 0, 0], [9, 0, 0, 0]],
                             jnp.int32)
        lens = jnp.asarray([14, 6, 0], jnp.int32)   # ragged + inactive
        return q, kp, vp, tables, lens

    def test_ref_matches_full_attention(self):
        """The paged gather+mask must equal dense softmax attention over
        each sequence's first seq_len tokens."""
        q, kp, vp, tables, lens = self._case()
        out = _paged_attention_ref(q, kp, vp, tables, lens,
                                   1.0 / np.sqrt(q.shape[-1]))
        ps = kp.shape[1]
        for b in range(q.shape[0]):
            n = int(lens[b])
            if n == 0:
                np.testing.assert_array_equal(np.asarray(out[b]), 0.0)
                continue
            k = jnp.concatenate([kp[p] for p in np.asarray(tables[b])],
                                axis=0)[:n]          # [n, H, hd]
            v = jnp.concatenate([vp[p] for p in np.asarray(tables[b])],
                                axis=0)[:n]
            s = jnp.einsum("hd,thd->ht", q[b].astype(jnp.float32),
                           k.astype(jnp.float32)) / np.sqrt(q.shape[-1])
            p_ = jax.nn.softmax(s, axis=-1)
            ref = jnp.einsum("ht,thd->hd", p_, v.astype(jnp.float32))
            np.testing.assert_allclose(np.asarray(out[b]), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)

    @pytest.mark.skipif(not paged_attention_available(),
                        reason="pallas unavailable")
    def test_kernel_matches_ref_interpret(self):
        q, kp, vp, tables, lens = self._case()
        scale = 1.0 / np.sqrt(q.shape[-1])
        ref = _paged_attention_ref(q, kp, vp, tables, lens, scale)
        ker = _paged_attention_kernel(q, kp, vp, tables, lens, scale,
                                      interpret=True)
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_public_entry_runs(self):
        q, kp, vp, tables, lens = self._case()
        out = paged_attention(q, kp, vp, tables, lens)
        assert out.shape == q.shape and out.dtype == q.dtype


# ------------------------------------------ ragged (fused prefill+decode)


class TestRaggedAttention:
    """The unified kernel: every batch row at an arbitrary position —
    mid-prefill chunk, decode step, or idle."""

    def _case(self, qlens, ctxs, Q=6, dtype=jnp.float32):
        B = len(qlens)
        H, hd, P, ps, M = 2, 8, 12, 4, 6
        ks = jax.random.split(jax.random.key(2), 3)
        q = jax.random.normal(ks[0], (B, Q, H, hd), dtype)
        kp = jax.random.normal(ks[1], (P, ps, H, hd), dtype)
        vp = jax.random.normal(ks[2], (P, ps, H, hd), dtype)
        rng = np.random.RandomState(0)
        tables = jnp.asarray(
            np.stack([rng.permutation(P)[:M] for _ in range(B)]), jnp.int32)
        return (q, kp, vp, tables, jnp.asarray(qlens, jnp.int32),
                jnp.asarray(ctxs, jnp.int32))

    def test_ref_matches_dense_causal_oracle(self):
        """Each query token must equal dense softmax attention over the
        kv prefix ending at its own absolute position (causal within
        the chunk, full context before it)."""
        # context lengths straddle the page_size=4 boundary: 7, 8, 9
        q, kp, vp, tables, qlens, ctxs = self._case([5, 1, 3, 0],
                                                    [7, 8, 9, 0])
        scale = 1.0 / np.sqrt(q.shape[-1])
        out = _ragged_attention_ref(q, kp, vp, tables, qlens, ctxs, scale)
        for b in range(q.shape[0]):
            ql, cl = int(qlens[b]), int(ctxs[b])
            k = jnp.concatenate([kp[p] for p in np.asarray(tables[b])], 0)
            v = jnp.concatenate([vp[p] for p in np.asarray(tables[b])], 0)
            for t in range(q.shape[1]):
                if t >= ql:
                    np.testing.assert_array_equal(np.asarray(out[b, t]),
                                                  0.0)
                    continue
                n = cl - ql + t + 1          # causal horizon of token t
                s = jnp.einsum("hd,thd->ht", q[b, t], k[:n]) * scale
                ref = jnp.einsum("ht,thd->hd", jax.nn.softmax(s, -1), v[:n])
                np.testing.assert_allclose(np.asarray(out[b, t]),
                                           np.asarray(ref),
                                           rtol=1e-5, atol=1e-5)

    @pytest.mark.skipif(not paged_attention_available(),
                        reason="pallas unavailable")
    def test_kernel_matches_ref_mixed_rows(self):
        """Interpret-mode kernel == ref for a batch mixing a mid-prefill
        chunk, a prompt-completing chunk, a decode row, and an idle row,
        with context lengths straddling page boundaries."""
        for qlens, ctxs in ([(5, 1, 3, 0), (14, 6, 3, 0)],
                            [(6, 6, 1, 1), (7, 8, 9, 24)],
                            [(1, 1, 1, 1), (4, 5, 16, 17)]):
            q, kp, vp, tables, ql, cl = self._case(list(qlens), list(ctxs))
            scale = 1.0 / np.sqrt(q.shape[-1])
            ref = _ragged_attention_ref(q, kp, vp, tables, ql, cl, scale)
            ker = _ragged_attention_kernel(q, kp, vp, tables, ql, cl,
                                           scale, interpret=True)
            np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)

    @pytest.mark.skipif(not paged_attention_available(),
                        reason="pallas unavailable")
    def test_decode_entry_is_qlen1_degenerate_row(self):
        """The legacy decode entry must equal a Q=1 ragged call."""
        q, kp, vp, tables, _, _ = self._case([1, 1, 1], [9, 4, 0], Q=1)
        lens = jnp.asarray([9, 4, 0], jnp.int32)
        scale = 1.0 / np.sqrt(q.shape[-1])
        dec = _paged_attention_kernel(q[:, 0], kp, vp, tables, lens, scale,
                                      interpret=True)
        rag = _ragged_attention_kernel(q, kp, vp, tables,
                                       (lens > 0).astype(jnp.int32), lens,
                                       scale, interpret=True)[:, 0]
        np.testing.assert_allclose(np.asarray(dec), np.asarray(rag),
                                   rtol=1e-6, atol=1e-6)


# ------------------------------------------------- continuous batching


class TestEngine:
    def test_greedy_matches_full_recompute_ragged(self, tiny_model):
        """Acceptance: ragged batch of 4 prompts, token-identical to the
        full-recompute oracle, with max_batch_size 2 forcing two of the
        requests to be admitted only after decoding has started."""
        cfg, params = tiny_model
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, cfg.vocab_size, n))
                   for n in (5, 11, 3, 17)]
        refs = [naive_generate(cfg, params, p, 8) for p in prompts]
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, prefill_len=32)
        outs = eng.generate(prompts, SamplingParams(max_new_tokens=8))
        assert outs == refs
        m = eng.metrics.snapshot()
        assert m["requests"]["finished"] == 4
        assert m["tokens"]["generated"] == 32
        assert eng.cache.num_free_pages == eng.cache.num_pages  # all freed

    def test_late_request_admitted_mid_decode(self, tiny_model):
        """Explicit continuous-batching check: a request submitted after
        several decode steps joins the in-flight batch, and neither it
        nor the already-running sequences diverge from their
        single-request outputs."""
        cfg, params = tiny_model
        rng = np.random.RandomState(7)
        early = [list(rng.randint(0, cfg.vocab_size, n)) for n in (6, 9)]
        late = list(rng.randint(0, cfg.vocab_size, 4))
        sp = SamplingParams(max_new_tokens=10)
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=4, prefill_len=32)
        reqs = [eng.add_request(p, sp) for p in early]
        for _ in range(3):
            eng.step()                        # decoding well underway
        assert all(len(r.output) >= 3 for r in reqs)
        late_req = eng.add_request(late, sp)
        while eng.has_work():
            eng.step()
        # the late request was admitted while others were mid-decode and
        # still matches its solo greedy output; so do the early ones
        assert late_req.output == naive_generate(cfg, params, late, 10)
        for r, p in zip(reqs, early):
            assert r.output == naive_generate(cfg, params, p, 10)

    def test_pool_exhaustion_rejects_gracefully(self, tiny_model):
        cfg, params = tiny_model
        eng = Engine(cfg, params, page_size=8, num_pages=4,
                     max_batch_size=2, prefill_len=32)   # 32-token pool
        r = eng.add_request(list(range(20)),
                            SamplingParams(max_new_tokens=20))
        assert r.state == RequestState.REJECTED
        assert "page pool exhausted" in r.finish_reason
        assert eng.metrics.requests_rejected.value == 1
        # a feasible request still runs fine afterwards
        out = eng.generate([list(range(8))],
                           SamplingParams(max_new_tokens=4))
        assert len(out[0]) == 4

    def test_preemption_recompute_is_lossless(self, tiny_model):
        """Two sequences that overflow the pool mid-decode: the youngest
        is preempted back to the queue, recomputed later, and its final
        output equals its uninterrupted solo run."""
        cfg, params = tiny_model
        rng = np.random.RandomState(3)
        p1 = list(rng.randint(0, cfg.vocab_size, 14))
        p2 = list(rng.randint(0, cfg.vocab_size, 14))
        eng = Engine(cfg, params, page_size=8, num_pages=6,
                     max_batch_size=2, prefill_len=32)
        sp = SamplingParams(max_new_tokens=20)
        outs = eng.generate([p1, p2], sp)
        assert eng.metrics.requests_preempted.value > 0
        assert outs[0] == naive_generate(cfg, params, p1, 20)
        assert outs[1] == naive_generate(cfg, params, p2, 20)

    def test_sampling_deterministic_under_fixed_seed(self, tiny_model):
        cfg, params = tiny_model
        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, cfg.vocab_size, n)) for n in (6, 12)]
        sp = SamplingParams(max_new_tokens=10, temperature=0.8, top_k=40,
                            top_p=0.9, seed=1234)
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, prefill_len=32)
        a = eng.generate(prompts, sp)
        b = eng.generate(prompts, sp)
        assert a == b
        # a different seed diverges (vocab 1024, 10 steps: collision odds
        # are negligible)
        sp2 = dataclasses.replace(sp, seed=99)
        c = eng.generate(prompts, sp2)
        assert c != a

    def test_stop_token_ends_generation(self, tiny_model):
        cfg, params = tiny_model
        prompt = list(range(4))
        first = naive_generate(cfg, params, prompt, 1)[0]
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=1, prefill_len=32)
        req = eng.add_request(prompt, SamplingParams(
            max_new_tokens=10, stop_token_ids=(first,)))
        while eng.has_work():
            eng.step()
        assert req.output == [first]
        assert req.finish_reason == "stop"

    def test_generation_predictor_api(self, tiny_model):
        cfg, params = tiny_model
        from paddle_tpu.inference import Config, create_predictor

        config = Config().enable_generation(
            cfg, params, page_size=8, num_pages=64, max_batch_size=2,
            prefill_len=32)
        pred = create_predictor(config)
        prompt = list(range(6))
        out = pred.generate([prompt], SamplingParams(max_new_tokens=5))
        assert out[0] == naive_generate(cfg, params, prompt, 5)
        snap = pred.metrics()
        assert snap["requests"]["finished"] == 1
        assert snap["ttft_s"]["count"] == 1


# ------------------------------------------------------- chunked prefill


class TestChunkedPrefill:
    """The unified-step scheduler: prompts become N bounded chunks
    interleaved with decode rows instead of one batch-stalling pass."""

    def test_long_prompt_chunked_greedy_parity(self, tiny_model):
        """Prompts straddling page boundaries, chunked 4 tokens at a
        time (page_size 8 — chunks cross pages mid-way), stay
        token-identical to the full-recompute oracle."""
        cfg, params = tiny_model
        rng = np.random.RandomState(11)
        prompts = [list(rng.randint(0, cfg.vocab_size, n))
                   for n in (15, 16, 17, 3)]
        refs = [naive_generate(cfg, params, p, 6) for p in prompts]
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=4)
        outs = eng.generate(prompts, SamplingParams(max_new_tokens=6))
        assert outs == refs
        m = eng.metrics.snapshot()
        assert m["tokens"]["prefill"] == sum(len(p) for p in prompts)
        # at least ceil(len / chunk_len) chunk rows per prompt (fair
        # sharing between concurrent prefills can split finer)
        assert m["tokens"]["prefill_chunks"] >= sum(
            -(-len(p) // 4) for p in prompts)
        assert eng.cache.num_free_pages == eng.cache.num_pages

    def test_prompt_longer_than_chunk_admitted(self, tiny_model):
        """The old prefill_len prompt-length rejection is gone: any
        prompt that fits max_seq_len is admitted and chunked."""
        cfg, params = tiny_model
        rng = np.random.RandomState(13)
        prompt = list(rng.randint(0, cfg.vocab_size, 100))
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=16)
        req = eng.add_request(prompt, SamplingParams(max_new_tokens=4))
        assert req.state == RequestState.QUEUED      # not rejected
        while eng.has_work():
            eng.step()
        assert req.state == RequestState.FINISHED
        assert req.output == naive_generate(cfg, params, prompt, 4)
        # infeasible-by-model-size is still rejected hard
        too_long = list(rng.randint(0, cfg.vocab_size, cfg.max_seq_len))
        rej = eng.add_request(too_long, SamplingParams(max_new_tokens=4))
        assert rej.state == RequestState.REJECTED

    def test_chunk_rows_interleave_with_decode_rows(self, tiny_model):
        """A long prompt arriving mid-decode prefills chunk-by-chunk in
        the same steps that keep decoding the running requests — and
        nobody's output diverges from its solo run."""
        cfg, params = tiny_model
        rng = np.random.RandomState(17)
        early = [list(rng.randint(0, cfg.vocab_size, n)) for n in (5, 7)]
        long_p = list(rng.randint(0, cfg.vocab_size, 24))
        sp = SamplingParams(max_new_tokens=10)
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=4, chunk_len=4)
        reqs = [eng.add_request(p, sp) for p in early]
        for _ in range(3):
            eng.step()
        assert all(len(r.output) >= 1 for r in reqs)
        before = [len(r.output) for r in reqs]
        late = eng.add_request(long_p, sp)
        eng.step()                            # late's first chunk runs...
        assert 0 < late.prompt_pos < len(long_p)
        after = [len(r.output) for r in reqs
                 if r.state == RequestState.RUNNING]
        # ...and every still-running early request still got its decode
        # token in that same step (no prefill stall)
        assert all(a > b for a, b in zip(after, before[:len(after)]))
        while eng.has_work():
            eng.step()
        assert late.output == naive_generate(cfg, params, long_p, 10)
        for r, p in zip(reqs, early):
            assert r.output == naive_generate(cfg, params, p, 10)

    def test_ttft_is_first_sampled_token(self, tiny_model):
        """serving_ttft_seconds must cover queueing + every chunk step:
        the first token exists only once the LAST chunk completed."""
        cfg, params = tiny_model

        class Clock:
            def __init__(self):
                self.t = 0.0

            def __call__(self):
                self.t += 1.0
                return self.t

        clk = Clock()
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=1, chunk_len=4, clock=clk)
        prompt = list(range(12))              # 3 chunks
        req = eng.add_request(prompt, SamplingParams(max_new_tokens=2))
        eng.step()
        assert req.prompt_pos == 4 and req.t_first_token is None
        assert eng.metrics.ttft.summary()["count"] == 0
        eng.step()
        assert req.prompt_pos == 8 and req.t_first_token is None
        eng.step()                            # completing chunk samples
        assert req.prompt_pos == 12
        assert req.t_first_token is not None
        assert len(req.output) == 1
        assert eng.metrics.ttft.summary()["count"] == 1
        assert eng.metrics.prefill_chunks.value == 3
        # tracer shows the chunked lifecycle, not a monolithic prefill
        while eng.has_work():
            eng.step()
        (tr,) = [t for t in eng.tracer.traces()
                 if t["name"] == f"request#{req.id}"]
        names = [s["name"] for s in tr["spans"]]
        assert {"chunk[0]", "chunk[1]", "chunk[2]", "decode[1]"} <= \
            set(names)
        assert "prefill" not in names

    def test_mid_prefill_deadline_eviction_frees_chunk_pages(self,
                                                             tiny_model):
        """Regression (this PR): a request evicted mid-prefill must
        return its already-written chunk pages to the pool."""
        cfg, params = tiny_model

        class ManualClock:
            def __init__(self):
                self.t = 0.0

            def advance(self, dt):
                self.t += dt

            def __call__(self):
                return self.t

        clk = ManualClock()
        eng = Engine(cfg, params, page_size=4, num_pages=32,
                     max_batch_size=2, chunk_len=4, clock=clk)
        req = eng.add_request(list(range(14)), SamplingParams(
            max_new_tokens=4, ttl_s=5.0))
        clk.advance(1.0)
        eng.step()                            # first chunk written
        assert req.state == RequestState.RUNNING
        assert 0 < req.prompt_pos < len(req.prompt)
        assert eng.cache.num_used_pages > 0
        clk.advance(10.0)                     # deadline passes mid-prefill
        done = eng.step()
        assert req in done
        assert req.state == RequestState.EVICTED
        assert req.finish_reason == "deadline"
        assert req.output == []               # never sampled
        assert eng.cache.num_free_pages == eng.cache.num_pages
        assert eng.metrics.deadline_evictions.value == 1

    def test_preemption_mid_prefill_is_lossless(self, tiny_model):
        """Memory pressure that preempts a request WHILE its prompt is
        still chunking must rewind chunk progress too: the recomputed
        request's greedy output equals its uninterrupted solo run."""
        cfg, params = tiny_model
        rng = np.random.RandomState(19)
        p_a = list(rng.randint(0, cfg.vocab_size, 8))
        p_b = list(rng.randint(0, cfg.vocab_size, 14))
        sp_a = SamplingParams(max_new_tokens=8)
        sp_b = SamplingParams(max_new_tokens=2)
        eng = Engine(cfg, params, page_size=4, num_pages=6,
                     max_batch_size=2, chunk_len=4)   # 24-token pool
        a = eng.add_request(p_a, sp_a)
        b = eng.add_request(p_b, sp_b)
        saw_mid_prefill_preemption = False
        while eng.has_work():
            pre = eng.metrics.requests_preempted.value
            mid = {r.id: 0 < r.prompt_pos < len(r.prompt)
                   for r in (a, b)}
            eng.step()
            if eng.metrics.requests_preempted.value > pre:
                # a preemption fired; was the rewound request mid-prefill?
                for r in (a, b):
                    if (r.state == RequestState.QUEUED and mid[r.id]
                            and r.prompt_pos == 0):
                        saw_mid_prefill_preemption = True
        assert eng.metrics.requests_preempted.value > 0
        assert saw_mid_prefill_preemption
        assert a.output == naive_generate(cfg, params, p_a, 8)
        assert b.output == naive_generate(cfg, params, p_b, 2)
        assert eng.cache.num_free_pages == eng.cache.num_pages

    def test_fair_chunk_budget_between_concurrent_prefills(self,
                                                           tiny_model):
        """A short prompt admitted while a long one is mid-prefill
        shares the chunk budget instead of starving behind it — its
        TTFT lands before the long prompt finishes prefilling."""
        cfg, params = tiny_model
        rng = np.random.RandomState(23)
        long_p = list(rng.randint(0, cfg.vocab_size, 60))
        short_p = list(rng.randint(0, cfg.vocab_size, 6))
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=8)
        sp = SamplingParams(max_new_tokens=4)
        long_r = eng.add_request(long_p, sp)
        eng.step()                            # long starts chunking
        assert 0 < long_r.prompt_pos < len(long_p)
        short_r = eng.add_request(short_p, sp)
        steps_to_short_ttft = 0
        while short_r.t_first_token is None and eng.has_work():
            eng.step()
            steps_to_short_ttft += 1
        assert short_r.t_first_token is not None
        assert long_r.prompt_pos < len(long_p)   # long still prefilling
        while eng.has_work():
            eng.step()
        assert short_r.output == naive_generate(cfg, params, short_p, 4)
        assert long_r.output == naive_generate(cfg, params, long_p, 4)


# -------------------------------------------- robustness under overload


class _ManualClock:
    """Deterministic engine clock: deadline tests advance time by hand
    instead of sleeping."""

    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


class TestDeadlineEviction:
    def test_running_request_evicted_mid_decode(self, tiny_model):
        cfg, params = tiny_model
        clk = _ManualClock()
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, prefill_len=32, clock=clk)
        req = eng.add_request(list(range(6)), SamplingParams(
            max_new_tokens=50, ttl_s=5.0))
        for _ in range(3):
            clk.advance(1.0)
            eng.step()
        assert req.state == RequestState.RUNNING
        produced = len(req.output)
        assert produced >= 3
        clk.advance(10.0)                  # now past the deadline
        done = eng.step()
        assert req in done
        assert req.state == RequestState.EVICTED
        assert req.finish_reason == "deadline"
        assert len(req.output) == produced   # partial output preserved
        # every page came back to the pool
        assert eng.cache.num_free_pages == eng.cache.num_pages
        assert eng.metrics.deadline_evictions.value == 1

    def test_queued_request_past_deadline_never_admitted(self, tiny_model):
        cfg, params = tiny_model
        clk = _ManualClock()
        # batch of 1: the second request waits in queue
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=1, prefill_len=32, clock=clk)
        sp_long = SamplingParams(max_new_tokens=30)
        sp_ttl = SamplingParams(max_new_tokens=4, ttl_s=2.0)
        eng.add_request(list(range(5)), sp_long)
        queued = eng.add_request(list(range(4)), sp_ttl)
        clk.advance(5.0)                   # queued request expires unseen
        eng.step()
        assert queued.state == RequestState.EVICTED
        assert queued.t_admitted is None   # evicted straight from queue
        assert queued.output == []

    def test_engine_default_ttl_applies(self, tiny_model):
        cfg, params = tiny_model
        clk = _ManualClock()
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, prefill_len=32, clock=clk,
                     default_ttl_s=1.0)
        req = eng.add_request(list(range(4)),
                              SamplingParams(max_new_tokens=50))
        assert req.deadline == pytest.approx(1.0)
        clk.advance(2.0)
        eng.step()
        assert req.state == RequestState.EVICTED


class TestWatermarkShedding:
    def test_queue_depth_watermarks_with_hysteresis(self, tiny_model):
        cfg, params = tiny_model
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=1, prefill_len=32,
                     shed_queue_high=3, shed_queue_low=1)
        sp = SamplingParams(max_new_tokens=3)
        reqs = [eng.add_request(list(range(4)), sp) for _ in range(6)]
        states = [r.state for r in reqs]
        # first three queue; hitting the high mark flips to shedding
        assert states[:3] == [RequestState.QUEUED] * 3
        assert states[3:] == [RequestState.RETRY_AFTER] * 3
        shed = reqs[3]
        assert shed.state != RequestState.REJECTED   # soft, not hard
        assert "retry" in shed.finish_reason
        assert eng.metrics.requests_shed.value == 3
        assert eng.metrics.engine_healthy.value == 0   # degraded
        # drain below the LOW mark: health recovers, admission resumes
        while eng.has_work():
            eng.step()
        assert eng.metrics.engine_healthy.value == 1
        ok = eng.add_request(list(range(4)), sp)
        assert ok.state == RequestState.QUEUED
        # admitted requests were unharmed by the overload
        for r in reqs[:3]:
            assert r.state == RequestState.FINISHED
            assert len(r.output) == 3

    def test_occupancy_watermark_sheds_until_pages_free(self, tiny_model):
        cfg, params = tiny_model
        eng = Engine(cfg, params, page_size=8, num_pages=4,
                     max_batch_size=2, prefill_len=16,
                     shed_occupancy_high=0.5, shed_occupancy_low=0.25)
        first = eng.add_request(list(range(10)),
                                SamplingParams(max_new_tokens=4))
        eng.step()                         # admitted: 2/4 pages in use
        assert eng.cache.occupancy() >= 0.5
        shed = eng.add_request(list(range(4)),
                               SamplingParams(max_new_tokens=2))
        assert shed.state == RequestState.RETRY_AFTER
        while eng.has_work():
            eng.step()                     # first finishes, pool drains
        assert first.state == RequestState.FINISHED
        late = eng.add_request(list(range(4)),
                               SamplingParams(max_new_tokens=2))
        assert late.state == RequestState.QUEUED

    def test_admitted_requests_meet_deadlines_under_shedding(self,
                                                            tiny_model):
        """The graceful-degradation contract: with shedding armed, what
        the engine ADMITS it finishes within TTL; overflow is shed with
        the soft status instead of destroying everyone's latency."""
        cfg, params = tiny_model
        clk = _ManualClock()
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, prefill_len=32, clock=clk,
                     default_ttl_s=60.0, shed_queue_high=2,
                     shed_queue_low=0)
        sp = SamplingParams(max_new_tokens=4)
        reqs = [eng.add_request(list(range(4)), sp) for _ in range(8)]
        while eng.has_work():
            clk.advance(1.0)               # 1 "second" per decode step
            eng.step()
        admitted = [r for r in reqs if r.state == RequestState.FINISHED]
        shed = [r for r in reqs if r.state == RequestState.RETRY_AFTER]
        assert admitted and shed
        assert len(admitted) + len(shed) == len(reqs)
        for r in admitted:                 # no admitted request blew its
            assert r.t_finished <= r.deadline   # deadline (none evicted)
        assert eng.metrics.deadline_evictions.value == 0

    def test_shedding_disabled_by_default(self, tiny_model):
        cfg, params = tiny_model
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=1, prefill_len=32)
        sp = SamplingParams(max_new_tokens=2)
        reqs = [eng.add_request(list(range(4)), sp) for _ in range(10)]
        assert all(r.state == RequestState.QUEUED for r in reqs)
        assert eng.metrics.engine_healthy.value == 1


class _AutoClock:
    """Manual clock that also self-advances per read — gives steps a
    deterministic nonzero duration so the decode-rate EWMA gets a real
    (and exactly reproducible) sample."""

    def __init__(self, auto=0.25):
        self.t = 0.0
        self.auto = auto

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        self.t += self.auto
        return self.t


class TestColdStartDrainFloor:
    """Regression (this PR): before the decode-rate EWMA has any
    sample, estimated_drain_s/retry_after_s used to report a useless 0
    — a freshly restarted replica looked instantly drainable and the
    router would dump the fleet's whole backlog on it.  The engine now
    reports a conservative configurable floor until the first measured
    decode step."""

    def test_floor_applies_until_first_decode_sample(self, tiny_model):
        cfg, params = tiny_model
        clk = _AutoClock(auto=0.25)
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=32, clock=clk,
                     drain_floor_s=3.0, shed_queue_high=1,
                     shed_queue_low=0)
        assert eng.decode_rate() is None
        # idle + cold: the floor, not 0
        assert eng.estimated_drain_s() == 3.0
        first = eng.add_request(list(range(6)),
                                SamplingParams(max_new_tokens=4))
        shed = eng.add_request(list(range(4)),
                               SamplingParams(max_new_tokens=4))
        assert shed.state == RequestState.RETRY_AFTER
        assert shed.retry_after_s >= 3.0      # the hint honors the floor
        while eng.has_work():
            eng.step()
        assert first.state == RequestState.FINISHED
        # a measured rate owns the estimate now: idle really means 0
        assert eng.decode_rate() is not None and eng.decode_rate() > 0
        assert eng.estimated_drain_s() == 0.0

    def test_floor_defaults_on_and_is_configurable(self, tiny_model):
        cfg, params = tiny_model
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=32)
        assert eng.drain_floor_s == Engine.DRAIN_FLOOR_S > 0
        assert eng.estimated_drain_s() == Engine.DRAIN_FLOOR_S
        off = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=32, drain_floor_s=0.0)
        assert off.estimated_drain_s() == 0.0

    def test_backlog_above_floor_still_wins(self, tiny_model):
        """The floor is a floor, not a cap: a cold engine with a big
        backlog reports the larger assumed-rate estimate."""
        cfg, params = tiny_model
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=32, drain_floor_s=0.1)
        eng.add_request(list(range(4)),
                        SamplingParams(max_new_tokens=100))
        expected = 100 / Engine.ASSUMED_DECODE_RATE      # 1.0 > 0.1
        assert eng.estimated_drain_s() == pytest.approx(expected)


# ----------------------------------------------------------- evacuation


class TestEvacuate:
    """Engine.evacuate() — the fleet router's failover/drain primitive:
    everything in flight comes off the engine with sampled tokens
    intact, pages freed, and a re-admission elsewhere continues
    token-identically."""

    def test_evacuate_returns_all_and_frees_pool(self, tiny_model):
        cfg, params = tiny_model
        rng = np.random.RandomState(29)
        p1 = list(rng.randint(0, cfg.vocab_size, 6))
        p2 = list(rng.randint(0, cfg.vocab_size, 20))   # mid-prefill
        p3 = list(rng.randint(0, cfg.vocab_size, 5))    # still queued
        # a dedicated tracer: the process-wide default ring holds other
        # tests' traces, whose root spans carry no "state" attribute
        from paddle_tpu.observability.tracing import Tracer

        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=8, tracer=Tracer())
        sp = SamplingParams(max_new_tokens=8)
        r1, r2, r3 = (eng.add_request(p, sp) for p in (p1, p2, p3))
        for _ in range(2):
            eng.step()
        assert r1.output                     # decoding
        assert 0 < r2.prompt_pos             # chunking
        assert r3.state == RequestState.QUEUED
        got = eng.evacuate()
        assert [r.id for r in got] == [r1.id, r2.id, r3.id]
        assert all(r.state == RequestState.EVACUATED for r in got)
        assert all(r.finish_reason == "evacuated" for r in got)
        assert eng.cache.num_free_pages == eng.cache.num_pages
        assert not eng.has_work()
        # traces closed in the terminal state
        states = {t["name"]: t["spans"][0]["attributes"]["state"]
                  for t in eng.tracer.traces()}
        assert states[f"request#{r1.id}"] == RequestState.EVACUATED

    def test_reenqueue_elsewhere_is_token_identical(self, tiny_model):
        """The idempotent re-enqueue contract: prompt + harvested
        tokens resubmitted to a fresh engine (KV rebuilt, never
        trusted) completes exactly the un-failed greedy output."""
        cfg, params = tiny_model
        rng = np.random.RandomState(31)
        prompt = list(rng.randint(0, cfg.vocab_size, 9))
        full = naive_generate(cfg, params, prompt, 10)
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=1, chunk_len=8)
        req = eng.add_request(prompt, SamplingParams(max_new_tokens=10))
        for _ in range(5):
            eng.step()
        (got,) = eng.evacuate()
        emitted = got.output
        assert 0 < len(emitted) < 10
        other = Engine(cfg, params, page_size=8, num_pages=64,
                       max_batch_size=1, chunk_len=8)
        rest = other.generate(
            [prompt + emitted],
            SamplingParams(max_new_tokens=10 - len(emitted)))[0]
        assert emitted + rest == full
        assert req is got


# ------------------------------------------------------- prefix cache


class TestPrefixCache:
    """Radix/prefix KV reuse: a shared prompt prefix becomes a refcount
    bump instead of prefill FLOPs — never a correctness change.  The
    parity oracle is the same full-recompute greedy decode every other
    engine test uses."""

    def _prompts(self, cfg, sys_len=12, tail_len=5, n_tails=2, seed=41):
        rng = np.random.RandomState(seed)
        system = [int(t) for t in rng.randint(0, cfg.vocab_size, sys_len)]
        tails = [[int(t) for t in rng.randint(0, cfg.vocab_size, tail_len)]
                 for _ in range(n_tails)]
        return system, tails

    # ---- cache-level mechanics -----------------------------------------
    def test_attach_refcounts_and_cow(self):
        c = PagedKVCache(num_layers=2, num_heads=2, head_dim=4,
                         num_pages=16, page_size=4, max_seq_len=64)
        toks = list(range(12))                   # 3 full pages
        assert c.allocate("a", 12)
        c.insert_prefix("a", toks)
        c.free("a")
        # cached pages are evictable, so the whole pool stays allocatable
        assert c.num_free_pages == 16
        assert c.prefix_stats()["cached_pages"] == 3
        c.check_integrity()
        # partial-prefix hit: 3 shared pages + 1 fresh for the tail
        m = c.allocate_prefixed("b", toks + [99, 98], chunk_tokens=4)
        assert m == 12
        shared = c.page_table("b")[:3]
        c.check_integrity()
        # full-prompt hit: matched is capped at len-1 and the final
        # page is COPIED, not shared — writes never land on shared pages
        m = c.allocate_prefixed("cw", toks, chunk_tokens=4)
        assert m == 11
        cow_table = c.page_table("cw")[:3]
        assert cow_table[:2] == shared[:2]       # prefix shared
        assert cow_table[2] != shared[2]         # final page is a copy
        np.testing.assert_array_equal(
            np.asarray(c.k_pages[:, cow_table[2]]),
            np.asarray(c.k_pages[:, shared[2]]))
        c.check_integrity()
        # free decrements; double-free impossible, cache intact
        c.free("b")
        c.free("cw")
        c.check_integrity()
        assert c.prefix_stats()["cached_pages"] == 3
        assert c.num_free_pages == 16

    def test_miss_returns_cold_and_shortage_rolls_back(self):
        c = PagedKVCache(num_layers=1, num_heads=1, head_dim=2,
                         num_pages=4, page_size=4, max_seq_len=16)
        assert c.allocate_prefixed("a", list(range(9)), 4) == 0  # cold
        # pool exhausted even after eviction: None, nothing moved
        assert c.allocate_prefixed("b", list(range(20, 36)), 16) is None
        assert "b" not in c.seq_ids()
        c.check_integrity()

    def test_pressure_eviction_never_reclaims_the_matched_chain(self):
        """Regression (review): allocation-pressure eviction used to
        run BEFORE the matched chain's refcounts were bumped, so a
        zero-ref matched page could be LRU-evicted and handed straight
        back as a "fresh" page for the SAME sequence — one physical
        page at two logical table positions (refcounts still
        consistent, so check_integrity alone missed it) and prefill
        writes corrupting what attention reads as the cached prefix.
        The chain is pinned first now; when the pinned match starves
        its own admission the match shrinks instead of corrupting."""
        c = PagedKVCache(num_layers=1, num_heads=1, head_dim=2,
                         num_pages=3, page_size=4, max_seq_len=16)
        toks = list(range(12))                   # 3 full pages
        assert c.allocate("a", 12)
        c.insert_prefix("a", toks)
        c.free("a")
        assert c.num_free_pages == 3             # pool = zero-ref cache
        # a 16-token prompt matching all 12 cached tokens needs 4
        # pages: the pool can only admit it by giving back part of the
        # match — never by evicting a page it is about to attach
        m = c.allocate_prefixed("b", toks + [99, 98, 97, 96],
                                chunk_tokens=8)
        assert m == 4                            # shrunk hit, not a dup
        table = c.page_table("b")[:3]
        assert len(set(table)) == len(table)     # no page twice
        c.check_integrity()

    def test_cow_source_pinned_and_shrunk_under_pressure(self):
        """Fully-cached prompt under total pool pressure: the COW
        source is pinned through the fresh-page take (it used to be
        evictable in the same window), and the admission falls back to
        a shorter shared prefix rather than failing or self-copying."""
        c = PagedKVCache(num_layers=1, num_heads=1, head_dim=2,
                         num_pages=3, page_size=4, max_seq_len=16)
        toks = list(range(12))
        assert c.allocate("a", 12)
        c.insert_prefix("a", toks)
        c.free("a")
        m = c.allocate_prefixed("cw", toks, chunk_tokens=4)
        # full COW needs matched-chain + copy page = 4 pages on a
        # 3-page pool: the deepest cached page is dropped, the first
        # two stay shared, the tail prefills into the reclaimed page
        assert m == 8
        table = c.page_table("cw")[:3]
        assert len(set(table)) == len(table)
        c.check_integrity()

    # ---- engine parity --------------------------------------------------
    def test_cache_hit_greedy_parity_and_metrics(self, tiny_model):
        """A request sharing a finished request's prefix prefills only
        its tail, and its greedy output equals a cold run's."""
        cfg, params = tiny_model
        system, tails = self._prompts(cfg)
        eng = Engine(cfg, params, page_size=4, num_pages=64,
                     max_batch_size=2, chunk_len=4)
        sp = SamplingParams(max_new_tokens=6)
        a = eng.add_request(system + tails[0], sp)
        while eng.has_work():
            eng.step()
        assert a.output == naive_generate(cfg, params, system + tails[0], 6)
        chunks_cold = eng.metrics.prefill_chunks.value
        b = eng.add_request(system + tails[1], sp)
        while eng.has_work():
            eng.step()
        assert b.output == naive_generate(cfg, params, system + tails[1], 6)
        snap = eng.metrics.snapshot()["prefix_cache"]
        assert snap["hits"] == 1
        assert snap["hit_tokens"] >= len(system) - eng.cache.page_size
        assert snap["cached_pages"] > 0
        # the hit skipped prefill work: fewer chunks than the cold run
        assert eng.metrics.prefill_chunks.value - chunks_cold < chunks_cold
        eng.cache.check_integrity()

    def test_full_prompt_hit_cow_parity(self, tiny_model):
        """An identical page-aligned prompt re-runs exactly one token
        through a copied final page — and decodes identically, without
        corrupting the original's cached pages for a third request."""
        cfg, params = tiny_model
        system, _ = self._prompts(cfg, sys_len=16, seed=43)  # 4 pages
        ref = naive_generate(cfg, params, system, 6)
        eng = Engine(cfg, params, page_size=4, num_pages=64,
                     max_batch_size=2, chunk_len=4)
        sp = SamplingParams(max_new_tokens=6)
        outs = [eng.generate([system], sp)[0] for _ in range(3)]
        assert outs == [ref, ref, ref]
        stats = eng.cache.prefix_stats()
        assert stats["hits"] == 2
        assert stats["hit_tokens"] == 2 * (len(system) - 1)  # COW cap
        eng.cache.check_integrity()

    def test_hit_mid_chunk_parity(self, tiny_model):
        """A cached prefix whose end is NOT a chunk boundary: prefill
        resumes mid-chunk at the first uncached token."""
        cfg, params = tiny_model
        # page 4, chunk 8: a 12-token cached prefix starts the tail
        # chunk at offset 12 % 8 == 4 — mid-chunk
        system, tails = self._prompts(cfg, sys_len=12, tail_len=9,
                                      seed=47)
        eng = Engine(cfg, params, page_size=4, num_pages=64,
                     max_batch_size=2, chunk_len=8)
        sp = SamplingParams(max_new_tokens=6)
        eng.generate([system + tails[0]], sp)
        b = eng.add_request(system + tails[1], sp)
        eng.step()
        assert b.prompt_pos > 12            # resumed past the cached part
        while eng.has_work():
            eng.step()
        assert b.output == naive_generate(cfg, params,
                                          system + tails[1], 6)
        assert eng.cache.prefix_stats()["hits"] == 1

    def test_prefix_cache_off_is_cold(self, tiny_model):
        cfg, params = tiny_model
        system, tails = self._prompts(cfg)
        eng = Engine(cfg, params, page_size=4, num_pages=64,
                     max_batch_size=2, chunk_len=4, prefix_cache=False)
        sp = SamplingParams(max_new_tokens=4)
        eng.generate([system + tails[0], system + tails[1]], sp)
        stats = eng.cache.prefix_stats()
        assert stats["hits"] == 0 and stats["cached_pages"] == 0
        assert eng.health()["prefix_cache"]["enabled"] is False

    # ---- eviction / watermark integration ------------------------------
    def test_lru_eviction_under_pressure_never_sheds(self, tiny_model):
        """A pool full of zero-ref cached prefixes must neither trip
        the occupancy watermark (no RETRY_AFTER storm from a warm
        cache) nor block admission: allocation LRU-evicts."""
        cfg, params = tiny_model
        rng = np.random.RandomState(53)
        eng = Engine(cfg, params, page_size=4, num_pages=8,
                     max_batch_size=1, chunk_len=8,
                     shed_occupancy_high=0.5)
        sp = SamplingParams(max_new_tokens=2)
        # two 16-token prompts fill all 8 pages with cached prefixes
        for _ in range(2):
            p = [int(t) for t in rng.randint(0, cfg.vocab_size, 15)]
            eng.generate([p], sp)
        assert eng.cache.prefix_stats()["cached_pages"] >= 6
        assert eng.cache.occupancy() == 0.0      # all evictable = free
        fresh = [int(t) for t in rng.randint(0, cfg.vocab_size, 15)]
        req = eng.add_request(fresh, sp)
        assert req.state == RequestState.QUEUED  # NOT shed
        while eng.has_work():
            eng.step()
        assert req.state == RequestState.FINISHED
        assert req.output == naive_generate(cfg, params, fresh, 2)
        assert eng.metrics.snapshot()["prefix_cache"]["evictions"] > 0
        assert eng.metrics.requests_shed.value == 0
        eng.cache.check_integrity()

    def test_mid_prefill_deadline_eviction_decrements_shared_pages(
            self, tiny_model):
        """The PR 7 eviction regression, extended: a request evicted
        mid-prefill whose already-written chunks include SHARED cached
        pages must DECREMENT them (the cache and its other users
        survive), not force-free them."""
        cfg, params = tiny_model
        system, tails = self._prompts(cfg, sys_len=12, tail_len=10,
                                      seed=59)
        clk = _ManualClock()
        eng = Engine(cfg, params, page_size=4, num_pages=32,
                     max_batch_size=2, chunk_len=4, clock=clk)
        sp = SamplingParams(max_new_tokens=4)
        a = eng.add_request(system + tails[0], sp)
        while eng.has_work():
            eng.step()
        cached = eng.cache.prefix_stats()["cached_pages"]
        assert cached > 0
        # B rides the cached prefix, then dies mid-prefill
        b = eng.add_request(system + tails[1],
                            SamplingParams(max_new_tokens=4, ttl_s=5.0))
        clk.advance(1.0)
        eng.step()
        assert b.prompt_pos > 12 and b.prompt_pos < len(b.prompt)
        clk.advance(10.0)
        done = eng.step()
        assert b in done and b.state == RequestState.EVICTED
        # shared pages survived the eviction: no double-free, cache
        # intact, and a third request still hits it with exact parity
        eng.cache.check_integrity()
        assert eng.cache.prefix_stats()["cached_pages"] >= cached
        assert eng.cache.num_free_pages == eng.cache.num_pages
        c = eng.add_request(system + tails[0], sp)
        while eng.has_work():
            eng.step()
        assert c.output == a.output
        assert eng.cache.prefix_stats()["hits"] >= 2
        eng.cache.check_integrity()

    # ---- defrag (satellite) --------------------------------------------
    def test_defrag_with_shared_prefix_decodes_token_identically(
            self, tiny_model):
        """Refcount-aware defrag: a page shared by two page tables (and
        the radix tree) relocates ONCE with every referencing table
        updated — both sequences keep decoding token-identically."""
        cfg, params = tiny_model
        system, tails = self._prompts(cfg, sys_len=12, tail_len=6,
                                      seed=61)
        eng = Engine(cfg, params, page_size=4, num_pages=64,
                     max_batch_size=2, chunk_len=16)
        sp = SamplingParams(max_new_tokens=10)
        # a placeholder allocation pins the low-index pages, so the
        # cached prefix and both sequences land above it — freeing it
        # later leaves the hole defrag must compact over
        eng.cache.allocate("hole", 16)
        eng.generate([system + [7, 7, 7]], SamplingParams(max_new_tokens=2))
        # two live sequences sharing the cached system prefix
        b = eng.add_request(system + tails[0], sp)
        c = eng.add_request(system + tails[1], sp)
        for _ in range(3):
            eng.step()
        assert b.output and c.output           # both mid-decode
        tb = eng.cache.page_table(b.id)[:3]
        assert tb[:3] == eng.cache.page_table(c.id)[:3]  # 2-way shared
        eng.cache.free("hole")                 # hole below everything
        moved = eng.cache.defrag()
        assert moved > 0
        assert eng.cache.page_table(b.id)[:3] != tb  # shared pages moved
        eng.cache.check_integrity()
        # the shared prefix relocated once: tables still agree
        assert eng.cache.page_table(b.id)[:3] == \
            eng.cache.page_table(c.id)[:3]
        while eng.has_work():
            eng.step()
        assert b.output == naive_generate(cfg, params, system + tails[0],
                                          10)
        assert c.output == naive_generate(cfg, params, system + tails[1],
                                          10)
        eng.cache.check_integrity()

    # ---- gossip surface -------------------------------------------------
    def test_prefix_summary_bounded_and_hashes_roundtrip(self, tiny_model):
        """The bounded radix summary names exactly the prefixes that
        prefix_hashes() computes client-side — the gossip protocol's
        two halves agree."""
        from paddle_tpu.serving import prefix_hashes

        cfg, params = tiny_model
        system, tails = self._prompts(cfg, sys_len=16, seed=67)
        eng = Engine(cfg, params, page_size=4, num_pages=64,
                     max_batch_size=2, chunk_len=8)
        eng.generate([system + tails[0]], SamplingParams(max_new_tokens=2))
        assert len(eng.prefix_summary(max_entries=3)["entries"]) <= 3
        summary = eng.prefix_summary()
        assert summary["enabled"] is True
        assert summary["stats"]["cached_pages"] > 0
        hashes = prefix_hashes(system + tails[1], summary["page_size"])
        depths = [(i + 1) * summary["page_size"]
                  for i, h in enumerate(hashes)
                  if h in summary["entries"]]
        assert depths and max(depths) >= 16      # the shared system part
        for h, depth in summary["entries"].items():
            assert depth % summary["page_size"] == 0


# --------------------------------------------------- satellite regressions


class TestRoiAlignEdge:
    def test_sample_exactly_at_image_edge_is_clamped_not_dropped(self):
        """A sampling point at exactly y == H (or x == W) must clamp onto
        the edge pixel (reference roi_align_op.cc zeroes only beyond ±1
        past the edge), not contribute zero."""
        from paddle_tpu.vision.detection_ops import roi_align

        feat = np.ones((1, 1, 4, 4), np.float32)
        # aligned: box (3.5, 3.5)-(4.5, 4.5) - 0.5 => y1=x1=3, y2=x2=4;
        # output 1x1, sampling_ratio 1 => single sample at (3.5+0.5)=4.0
        boxes = np.asarray([[3.5, 3.5, 4.5, 4.5]], np.float32)
        out = roi_align(feat, boxes, output_size=1, sampling_ratio=1,
                        aligned=True)
        assert float(np.asarray(out)[0, 0, 0, 0]) == pytest.approx(1.0)

    def test_sample_beyond_edge_still_zero(self):
        from paddle_tpu.vision.detection_ops import roi_align

        feat = np.ones((1, 1, 4, 4), np.float32)
        # sample lands at 5.5 > H + 1: stays invalid
        boxes = np.asarray([[5.0, 5.0, 6.0, 6.0]], np.float32)
        out = roi_align(feat, boxes, output_size=1, sampling_ratio=1,
                        aligned=True)
        assert float(np.asarray(out)[0, 0, 0, 0]) == 0.0


class TestConll05Guard:
    def _emit(self, sent, cols):
        from paddle_tpu.text import Conll05

        ds = object.__new__(Conll05)
        ds.samples = []
        ds.word_dict = ds.label_dict = None
        ds._emit(sent, cols)
        return ds.samples

    def test_well_formed_rows_parse(self):
        samples = self._emit(
            ["the", "cat", "sat"],
            [["-", "(A0*"], ["-", "*)"], ["sat", "(V*)"]])
        assert len(samples) == 1
        words, pred, labels = samples[0]
        assert pred == "sat"
        assert labels == ["B-A0", "I-A0", "B-V"]

    def test_malformed_short_row_raises_descriptive_error(self):
        with pytest.raises(ValueError, match="malformed props row"):
            self._emit(["the", "cat", "sat"],
                       [["-", "(A0*"], ["-"], ["sat", "(V*)"]])
