"""SLO engine matrix — burn-rate alerts, budgets, endpoints, and the
autoscaler coupling, all on a manual clock.

The alert state machine is driven beat by beat through a scripted
traffic history: a 100%-bad storm fires the fast-burn page exactly once
(fire-once/sticky), the alert stays active while the storm holds, does
NOT clear before ``clear_after_seconds`` of continuously-healthy short
window, then clears exactly once — and every transition lands in the
metrics, the ``/slo`` payload, and a tail-retained ``slo::<name>``
span.  The autoscaler acceptance: a firing TTFT fast-burn page scales
the fleet up under pressure the hysteresis band alone would ignore,
and a degraded error budget blocks scale-down.
"""
import json
import urllib.error
import urllib.request

import pytest

from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.slo import (SEVERITIES, SLO, BurnRateAlert,
                                          SLOEngine)
from paddle_tpu.observability.timeseries import TimeSeriesStore
from paddle_tpu.observability.tracing import Tracer
from paddle_tpu.observability.exporter import start_telemetry_server
from paddle_tpu.serving import Autoscaler, FleetRouter, RequestState


class _ManualClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _page_alert(**kw):
    spec = dict(burn_rate_threshold=5.0, long_window_seconds=4.0,
                short_window_seconds=1.0, clear_after_seconds=1.0)
    spec.update(kw)
    return BurnRateAlert("page", **spec)


def _availability_engine(clock, *, tracer=None, registry=None):
    """req/bad counters + one availability SLO with a tight page."""
    reg = registry or MetricsRegistry()
    req = reg.counter("req_total")
    bad = reg.counter("bad_total")
    store = TimeSeriesStore(registry=reg, clock=clock)
    slo = SLO("availability", target=0.9, bad="bad_total",
              total="req_total", alerts=(_page_alert(),),
              budget_window_seconds=60.0)
    engine = SLOEngine(store, [slo], registry=reg, tracer=tracer,
                       clock=clock)
    return reg, req, bad, store, engine


def _beat(clock, store, engine, req, bad, n_req, n_bad, dt=0.5):
    clock.advance(dt)
    req.inc(n_req)
    bad.inc(n_bad)
    store.scrape_once()
    return engine.evaluate()


# --------------------------------------------------------- declarations


class TestDeclarations:
    def test_severity_enum_is_fixed(self):
        assert SEVERITIES == ("page", "ticket")
        with pytest.raises(ValueError):
            BurnRateAlert("warning", burn_rate_threshold=1.0,
                          long_window_seconds=60.0,
                          short_window_seconds=5.0)

    def test_short_window_must_be_shorter(self):
        with pytest.raises(ValueError):
            BurnRateAlert("page", burn_rate_threshold=1.0,
                          long_window_seconds=5.0,
                          short_window_seconds=5.0)

    def test_slo_name_must_be_snake_case(self):
        with pytest.raises(ValueError):
            SLO("TTFT-p99", target=0.99, bad="b_total", total="t_total")

    def test_target_bounds(self):
        for target in (0.0, 1.0, 1.5):
            with pytest.raises(ValueError):
                SLO("ttft", target=target, bad="b_total",
                    total="t_total")

    def test_exactly_one_form(self):
        with pytest.raises(ValueError):
            SLO("x", target=0.9)                      # no form at all
        with pytest.raises(ValueError):
            SLO("x", target=0.9, good="g_total", bad="b_total",
                total="t_total")                      # two forms
        with pytest.raises(ValueError):
            SLO("x", target=0.9, histogram="lat_seconds")  # no threshold

    def test_duplicate_slo_names_rejected(self):
        clock = _ManualClock()
        store = TimeSeriesStore(registry=MetricsRegistry(), clock=clock)
        slos = [SLO("a", target=0.9, bad="b_total", total="t_total"),
                SLO("a", target=0.5, bad="b_total", total="t_total")]
        with pytest.raises(ValueError):
            SLOEngine(store, slos, registry=MetricsRegistry())

    def test_default_alert_pair_is_workbook_shaped(self):
        slo = SLO("avail", target=0.999, bad="b_total", total="t_total")
        sevs = [a.severity for a in slo.alerts]
        assert sevs == ["page", "ticket"]
        page, ticket = slo.alerts
        assert page.burn_rate_threshold > ticket.burn_rate_threshold
        assert page.long_window_seconds < ticket.long_window_seconds


# ------------------------------------------------- alert state machine


class TestAlertStateMachine:
    def test_fire_once_sticky_hysteresis_clear(self):
        clock = _ManualClock()
        tracer = Tracer()
        reg, req, bad, store, engine = _availability_engine(
            clock, tracer=tracer)
        # healthy traffic: no alert ever
        for _ in range(10):
            assert _beat(clock, store, engine, req, bad, 10, 0) == []
        assert engine.alerts_active() == []
        assert engine.page_active() is False

        # 100%-bad storm: burn 10x on both windows once the long
        # window is majority-bad -> exactly ONE fire event
        fires = []
        for _ in range(12):                        # 6 s of storm
            fires += _beat(clock, store, engine, req, bad, 10, 10)
        assert [t["transition"] for t in fires] == ["fire"]
        assert fires[0]["slo"] == "availability"
        assert fires[0]["severity"] == "page"
        assert engine.page_active() is True
        assert engine.alerts_active() == [("availability", "page")]

        # storm ends; the short window drains within 1 s, but the
        # clear must wait out clear_after_seconds of continuously
        # healthy short window — no flap
        clears = []
        beats_to_clear = 0
        for _ in range(20):
            tr = _beat(clock, store, engine, req, bad, 10, 0)
            beats_to_clear += 1
            if tr:
                clears += tr
                break
        assert [t["transition"] for t in clears] == ["clear"]
        # >= short window (1 s) to drain + 1 s hysteresis at 0.5 s
        # beats: never clears on the first beats after the storm
        assert beats_to_clear >= 4
        assert engine.page_active() is False
        # sticky bookkeeping: one onset, one fire
        st = engine.status()["slos"]["availability"]["alerts"][0]
        assert st["fired"] == 1 and st["active"] is False

        # every transition became a tail-retained slo:: span
        spans = [t for t in tracer.traces()
                 if t["name"] == "slo::availability"]
        assert len(spans) == 2
        assert all(t["retained"] == "flagged" for t in spans)
        kinds = [t["spans"][0]["attributes"]["transition"]
                 for t in spans]
        assert kinds == ["fire", "clear"]

    def test_refire_after_second_onset(self):
        clock = _ManualClock()
        reg, req, bad, store, engine = _availability_engine(clock)
        for _ in range(4):
            _beat(clock, store, engine, req, bad, 10, 0)
        for storm in range(2):
            for _ in range(12):
                _beat(clock, store, engine, req, bad, 10, 10)
            for _ in range(20):
                if _beat(clock, store, engine, req, bad, 10, 0):
                    break
        st = engine.status()["slos"]["availability"]["alerts"][0]
        assert st["fired"] == 2
        kinds = [t["transition"]
                 for t in engine.status()["transitions"]]
        assert kinds == ["fire", "clear", "fire", "clear"]

    def test_long_window_vetoes_blip(self):
        """A single bad beat spikes the short window but not the
        4 s long window: no page — sustained damage is required."""
        clock = _ManualClock()
        reg, req, bad, store, engine = _availability_engine(clock)
        for _ in range(10):
            _beat(clock, store, engine, req, bad, 10, 0)
        assert _beat(clock, store, engine, req, bad, 10, 10) == []
        for _ in range(3):
            assert _beat(clock, store, engine, req, bad, 10, 0) == []
        assert engine.alerts_active() == []

    def test_no_traffic_is_not_an_outage(self):
        clock = _ManualClock()
        reg, req, bad, store, engine = _availability_engine(clock)
        for _ in range(10):
            clock.advance(0.5)
            store.scrape_once()
            assert engine.evaluate() == []
        assert engine.page_active() is False
        assert engine.min_budget_ratio() == 1.0

    def test_metrics_published_on_evaluate(self):
        clock = _ManualClock()
        reg, req, bad, store, engine = _availability_engine(clock)
        for _ in range(12):
            _beat(clock, store, engine, req, bad, 10, 10)
        fired = reg.counter(
            "slo_alerts_total",
            labelnames=("slo", "severity")).labels(
                slo="availability", severity="page").value
        assert fired == 1
        active = reg.gauge(
            "slo_alert_active",
            labelnames=("slo", "severity")).labels(
                slo="availability", severity="page").value
        assert active == 1.0
        assert reg.gauge("slo_page_active").value == 1.0
        burn = reg.gauge(
            "slo_burn_rate", labelnames=("slo", "window")).labels(
                slo="availability", window="1s").value
        assert burn == pytest.approx(10.0)
        budget = reg.gauge(
            "slo_error_budget_ratio", labelnames=("slo",)).labels(
                slo="availability").value
        assert budget < 1.0

    def test_budget_drains_with_bad_fraction(self):
        clock = _ManualClock()
        reg, req, bad, store, engine = _availability_engine(clock)
        for _ in range(4):
            _beat(clock, store, engine, req, bad, 10, 0)
        healthy = engine.min_budget_ratio()
        assert healthy == 1.0
        for _ in range(12):
            _beat(clock, store, engine, req, bad, 10, 10)
        assert engine.min_budget_ratio() < healthy
        assert engine.min_budget_ratio() == 0.0   # 10x overspend


# --------------------------------------------------- histogram-form SLO


class TestLatencySLO:
    def test_ttft_threshold_objective_fires_on_slow_tail(self):
        clock = _ManualClock()
        reg = MetricsRegistry()
        # bucket upper bounds 0.05, 0.1, 0.2, 0.4
        ttft = reg.histogram("serving_ttft_seconds", start=0.05,
                             factor=2.0, count=4)
        store = TimeSeriesStore(registry=reg, clock=clock)
        slo = SLO("ttft_fast", target=0.9,
                  histogram="serving_ttft_seconds",
                  threshold_seconds=0.1, alerts=(_page_alert(),),
                  budget_window_seconds=60.0)
        engine = SLOEngine(store, [slo], registry=reg, clock=clock)
        for _ in range(6):                         # fast: all good
            clock.advance(0.5)
            ttft.observe(0.06)
            store.scrape_once()
            assert engine.evaluate() == []
        fires = []
        for _ in range(12):                        # slow tail storm
            clock.advance(0.5)
            ttft.observe(0.35)
            store.scrape_once()
            fires += engine.evaluate()
        assert [t["transition"] for t in fires] == ["fire"]
        assert engine.page_active() is True


# ------------------------------------------------------------ endpoints


class TestEndpoints:
    def test_slo_timeseries_and_healthz_fold(self):
        clock = _ManualClock()
        reg, req, bad, store, engine = _availability_engine(clock)
        srv = start_telemetry_server(port=0, registry=reg,
                                     tracer=Tracer(), slo=engine,
                                     timeseries=store)
        try:
            for _ in range(4):
                _beat(clock, store, engine, req, bad, 10, 0)
            code, body = _get(srv.url + "/slo")
            assert code == 200
            payload = json.loads(body)
            assert payload["page_active"] is False
            assert payload["slos"]["availability"]["target"] == 0.9
            code, body = _get(srv.url + "/healthz")
            assert code == 200
            assert json.loads(body)["slo_page_active"] is False

            code, body = _get(srv.url + "/timeseries")
            assert code == 200
            assert json.loads(body)["series"] >= 2
            code, body = _get(
                srv.url + "/timeseries?name=req_total&window_seconds=4")
            assert code == 200
            q = json.loads(body)
            assert q["kind"] == "counter" and q["delta"] == 30.0

            for _ in range(12):                   # storm -> page
                _beat(clock, store, engine, req, bad, 10, 10)
            code, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 503
            assert health["healthy"] is False
            assert health["slo_page_active"] is True
            code, body = _get(srv.url + "/slo")
            payload = json.loads(body)
            assert payload["page_active"] is True
            assert [t["transition"]
                    for t in payload["transitions"]] == ["fire"]

            for _ in range(20):                   # recover -> clear
                if _beat(clock, store, engine, req, bad, 10, 0):
                    break
            code, _ = _get(srv.url + "/healthz")
            assert code == 200
        finally:
            srv.stop()

    def test_healthz_gauge_fallback_without_engine(self):
        reg = MetricsRegistry()
        reg.gauge("slo_page_active").set(1)
        srv = start_telemetry_server(port=0, registry=reg,
                                     tracer=Tracer())
        try:
            code, body = _get(srv.url + "/healthz")
            assert code == 503
            assert json.loads(body)["slo_page_active"] is True
        finally:
            srv.stop()

    def test_endpoints_404_when_not_attached(self):
        srv = start_telemetry_server(port=0, registry=MetricsRegistry(),
                                     tracer=Tracer())
        try:
            assert _get(srv.url + "/slo")[0] == 404
            assert _get(srv.url + "/timeseries")[0] == 404
        finally:
            srv.stop()


# -------------------------------------------------- autoscaler coupling


class _StubEngine:
    """Router-facing engine stub (mirrors test_autoscaler's)."""

    def __init__(self, rate=120.0, drain=0.0):
        self.rate = rate
        self.drain = drain
        self.reqs = []

    def health(self):
        return {"healthy": True, "queue_depth": 0,
                "running": len(self.reqs), "page_occupancy": 0.0,
                "estimated_drain_s": self.drain,
                "decode_rate_tok_s": self.rate,
                "prefix_cache": {"enabled": True}}

    def add_request(self, prompt, sampling, trace_context=None):
        raise AssertionError("no traffic in these tests")

    def has_work(self):
        return False

    def step(self):
        pass

    def evacuate(self):
        self.reqs = []

    def prefix_summary(self, max_entries=32):
        return {"page_size": 8, "enabled": True, "entries": {},
                "stats": {}}

    def warmup(self):
        return self


class _StubSLO:
    """SLOEngine-shaped stub: the autoscaler only reads
    ``alerts_active()`` and ``min_budget_ratio()``."""

    def __init__(self, alerts=(), budget=1.0):
        self.alerts = list(alerts)
        self.budget = budget

    def alerts_active(self):
        return list(self.alerts)

    def min_budget_ratio(self):
        return self.budget


def _fleet(engines, clock, *, registry=None, scaler_kw=None):
    registry = registry or MetricsRegistry()
    router = FleetRouter(engines, clock=clock, registry=registry)
    kw = dict(min_replicas=1, max_replicas=4, up_pressure_s=2.0,
              down_pressure_s=0.25, up_pending_depth=6,
              scale_up_cooldown_s=5.0, scale_down_cooldown_s=10.0,
              spawn_backoff_base_s=0.001, spawn_backoff_cap_s=0.002)
    kw.update(scaler_kw or {})
    scaler = Autoscaler(router, lambda: _StubEngine(),
                        clock=clock, registry=registry, **kw)
    return router, scaler


class TestAutoscalerSLOCoupling:
    def test_firing_ttft_page_escalates_scale_up(self):
        """THE acceptance scenario: pressure sits inside the
        hysteresis band (no up on its own), but a real TTFT fast-burn
        page is firing — the autoscaler scales up with reason
        ``slo_fast_burn``."""
        clock = _ManualClock()
        reg = MetricsRegistry()
        ttft = reg.histogram("serving_ttft_seconds", start=0.05,
                             factor=2.0, count=4)
        store = TimeSeriesStore(registry=reg, clock=clock)
        slo = SLO("ttft_fast", target=0.9,
                  histogram="serving_ttft_seconds",
                  threshold_seconds=0.1, alerts=(_page_alert(),),
                  budget_window_seconds=60.0)
        slo_engine = SLOEngine(store, [slo], registry=reg, clock=clock)
        stub = _StubEngine(drain=1.0)              # inside the band
        router, scaler = _fleet([stub], clock, registry=reg,
                                scaler_kw={"slo": slo_engine})
        # control first: same pressure, page not yet firing -> no act
        clock.advance(1.0)
        assert scaler.tick() is None
        for _ in range(12):                        # slow-TTFT storm
            clock.advance(0.5)
            ttft.observe(0.35)
            store.scrape_once()
            slo_engine.evaluate()
        assert slo_engine.page_active() is True
        clock.advance(5.0)                         # up cooldown clear
        assert scaler.tick() == ("up", "slo_fast_burn")
        assert len(router.replicas) == 2
        sig = scaler.status()["last_signals"]
        assert sig["slo_page"] is True
        assert sig["pressure_s"] < scaler.up_pressure_s

    def test_pressure_alone_would_not_have_acted(self):
        """The identical fleet WITHOUT the SLO engine stays put under
        the same pressure — the page was the only reason to scale."""
        clock = _ManualClock()
        stub = _StubEngine(drain=1.0)
        router, scaler = _fleet([stub], clock)
        clock.advance(10.0)
        assert scaler.tick() is None
        assert len(router.replicas) == 1

    def test_active_alert_blocks_scale_down(self):
        clock = _ManualClock()
        stubs = [_StubEngine(drain=0.0), _StubEngine(drain=0.0)]
        slo = _StubSLO(alerts=[("availability", "ticket")])
        router, scaler = _fleet(stubs, clock,
                                scaler_kw={"slo": slo})
        clock.advance(30.0)
        assert scaler.tick() is None               # even a ticket vetoes
        slo.alerts = []
        clock.advance(30.0)
        assert scaler.tick() == ("down", "idle")

    def test_thin_budget_blocks_scale_down_until_it_refills(self):
        clock = _ManualClock()
        stubs = [_StubEngine(drain=0.0), _StubEngine(drain=0.0)]
        slo = _StubSLO(budget=0.1)                 # below the 0.25 floor
        router, scaler = _fleet(stubs, clock,
                                scaler_kw={"slo": slo})
        clock.advance(30.0)
        assert scaler.tick() is None
        assert scaler.status()["last_signals"]["slo_min_budget"] == 0.1
        slo.budget = 0.9
        clock.advance(30.0)
        assert scaler.tick() == ("down", "idle")

    def test_windowed_shed_signal_replaces_adhoc_delta(self):
        """With a store attached the shed signal is a
        ``signal_window_s`` delta: a shed burst triggers up, and once
        the burst ages out of the window the signal reads zero again
        regardless of tick cadence."""
        clock = _ManualClock()
        reg = MetricsRegistry()
        stub = _StubEngine(drain=0.0)
        store = TimeSeriesStore(registry=reg, clock=clock)
        router, scaler = _fleet(
            [stub], clock, registry=reg,
            scaler_kw={"timeseries": store, "signal_window_s": 2.0,
                       "scale_down_cooldown_s": 10_000.0})
        shed = reg.counter("router_backpressure_retries_total",
                           labelnames=("replica",))
        # the replica-0 child series is born on its first inc; the
        # windowed delta needs two points of THAT series
        shed.labels(replica="0").inc()
        clock.advance(1.0)
        store.scrape_once()
        shed.labels(replica="0").inc()
        clock.advance(0.5)
        store.scrape_once()
        assert scaler.tick() == ("up", "shed")
        assert scaler.status()["last_signals"]["shed_delta"] == 1.0
        # the burst ages out of the 2 s window -> no more up events
        clock.advance(10.0)
        store.scrape_once()
        clock.advance(0.5)
        store.scrape_once()
        assert scaler.tick() is None
        assert scaler.status()["last_signals"]["shed_delta"] == 0.0


# ------------------------------------------------------- overhead smoke


class TestSLOOverheadSmoke:
    def test_scrape_evaluate_cycle_under_bound(self):
        """Acceptance: a full store-scrape + 3-objective evaluate
        cycle over a serving-shaped registry stays under the 1%%
        bound ``bench --section slo`` publishes (50 ms request
        model).  Runs in a fresh subprocess: a mid-suite interpreter
        carries daemon threads from earlier test modules whose GIL
        share uniformly inflates every cycle ~2x — that measures the
        test session, not the engine."""
        import json
        import os
        import subprocess
        import sys

        root = os.path.join(os.path.dirname(__file__), os.pardir)
        code = (
            "import importlib.util, json, sys\n"
            "spec = importlib.util.spec_from_file_location("
            "'bench_mod', sys.argv[1])\n"
            "bench = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(bench)\n"
            "print(json.dumps(bench.bench_slo()))\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", code,
             os.path.join(root, "bench.py")],
            capture_output=True, text=True, timeout=300, cwd=root,
            env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["implied_request_overhead_ratio"] < \
            out["bound_ratio"], out
        # absolute sanity: sub-millisecond per cycle
        assert out["per_cycle_us"] < 5000, out
        # the bench fleet is healthy: no page firing at the end
        assert out["page_active"] is False, out
