"""Compressed chaos soak — the tier-1 variant of ``bench.py --section
soak``: a seeded diurnal/bursty trace through an autoscaled real-engine
fleet while the chaos timeline fires a hard kill, admission and
control-loop stalls, and a spawn io_error (the fault sites
``autoscaler.poll`` / ``autoscaler.scale_up`` / ``serving.admit``),
asserting the invariants end-to-end: ``lost_requests == 0``, bounded
TTFT p99, at least one scale-up AND one scale-down recorded in the
live-scraped ``/fleet``, every chaos event visible in ``/flight``.
"""
import dataclasses

import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPT_CONFIGS, gpt_init
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.serving import ChaosEvent, Engine, TrafficGenerator, run_soak


def _tiny_cfg():
    return dataclasses.replace(GPT_CONFIGS["tiny"], dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = gpt_init(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


def _engine_factory(tiny_model):
    cfg, params = tiny_model

    def factory():
        # a small queue watermark so the burst actually sheds — the
        # RETRY_AFTER signal is one of the scale-up triggers under test
        return Engine(cfg, params, page_size=8, num_pages=64,
                      max_batch_size=2, chunk_len=8,
                      shed_queue_high=4, shed_queue_low=1)
    return factory


@pytest.mark.faultinject
class TestCompressedSoak:
    def test_chaos_soak_invariants(self, tiny_model):
        traffic = TrafficGenerator(
            base_rate_per_s=6.0, diurnal_amplitude=0.9,
            day_period_s=8.0, phase_s=0.0,
            bursts=((1.0, 2.0, 4.0),),          # spike at t in [1, 3)
            n_cohorts=2, cohort_prefix_len=16, cohort_fraction=0.6,
            prompt_len=(8, 24), max_new_tokens=(4, 6),
            vocab_size=_tiny_cfg().vocab_size, seed=1234)
        chaos = [
            ChaosEvent(t=0.5, action="spawn_io_error"),
            ChaosEvent(t=1.5, action="stall_admit", stall_s=0.4),
            ChaosEvent(t=2.5, action="kill"),
            ChaosEvent(t=3.0, action="stall_poll", stall_s=0.3),
        ]
        report = run_soak(
            _engine_factory(tiny_model), traffic, horizon_s=8.0,
            initial_replicas=2, chaos=chaos,
            registry=MetricsRegistry(),
            scaler_kw=dict(min_replicas=1, max_replicas=3,
                           up_pressure_s=1.0, down_pressure_s=0.15,
                           up_pending_depth=4,
                           scale_up_cooldown_s=1.5,
                           scale_down_cooldown_s=2.0,
                           spawn_max_retries=2,
                           spawn_backoff_base_s=0.01,
                           spawn_backoff_cap_s=0.05),
            deadline_s=40.0, grace_s=8.0, min_down_events=1,
            ttft_bound_s=25.0)

        # ---- zero loss through kills, stalls, drains, scale events
        assert not report["timed_out"], report
        assert report["requests_submitted"] > 20
        assert report["lost_requests"] == 0, report
        assert report["requests_finished"] == report["requests_submitted"]

        # ---- bounded TTFT p99 (recoveries cost latency, never
        # starvation)
        assert report["ttft_p99_s"] is not None
        assert report["ttft_p99_ok"], report["ttft_p99_s"]

        # ---- elasticity both ways, mid-trace
        events = report["scale_events"]
        assert events.get("up", 0) >= 1, events
        assert events.get("down", 0) >= 1, events
        assert events.get("up", 0) + events.get("down", 0) >= 2

        # ---- the whole kill matrix actually fired
        assert all(ev["action"] in ("kill", "stall_admit", "stall_poll",
                                    "spawn_io_error")
                   for ev in report["chaos"])
        assert len(report["chaos"]) == 4
        fired_sites = {f["site"] for f in report["injector_fired"]}
        assert "serving.admit" in fired_sites
        assert "autoscaler.poll" in fired_sites
        assert "autoscaler.scale_up" in fired_sites
        # the killed replica's in-flight work was re-dispatched (unless
        # it happened to be idle at kill time — redispatch also comes
        # from drains, so usually > 0)
        assert report["redispatched"] >= 0

        # ---- recoveries visible over live HTTP: /fleet carries the
        # autoscaler block with both directions, /flight the chaos
        # timeline
        scraped = report["scraped"]
        fleet = scraped["fleet"]
        assert fleet["autoscaler"]["scale_events"]["up"] >= 1
        assert fleet["autoscaler"]["scale_events"]["down"] >= 1
        assert fleet["counters"]["lost"] == 0
        flight = scraped["flight"]
        flight_ops = {rec["op"] for rec in flight["records"]}
        flight_ops |= set(flight["summary"]["by_op"])
        soak_ops = {op for op in flight_ops if op.startswith("soak::")}
        assert {"soak::kill", "soak::stall_admit", "soak::stall_poll",
                "soak::spawn_io_error"} <= soak_ops, flight_ops

        # ---- merged fleet trace view over live HTTP: a hard-killed-
        # and-failed-over request reads as ONE trace — one entry per
        # trace_id, the failover hop and both dispatches on it
        traces = scraped["traces"]
        assert traces["fleet"] is True
        merged = traces["traces"]
        tids = [t["trace_id"] for t in merged]
        assert len(tids) == len(set(tids)), "trace split across entries"
        if report["redispatched"]:
            failed_over = [
                t for t in merged
                if any(s["name"] == "router::failover"
                       for s in t["spans"])]
            assert failed_over, "redispatches left no failover trace"
            for t in failed_over:
                names = [s["name"] for s in t["spans"]]
                assert names.count("router::dispatch") >= 2, names
                # tail retention pinned it (failover, or a stronger
                # reason like a fault event recorded on a span)
                assert t["retained"] != "sampled", t["retained"]
