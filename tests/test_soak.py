"""Compressed chaos soak — the tier-1 variant of ``bench.py --section
soak``: a seeded diurnal/bursty trace through an autoscaled real-engine
fleet while the chaos timeline fires a hard kill, admission and
control-loop stalls, a spawn io_error (the fault sites
``autoscaler.poll`` / ``autoscaler.scale_up`` / ``serving.admit``),
and a live-state ``bitflip`` at ``serving.step``, asserting the
invariants end-to-end: ``lost_requests == 0``, bounded TTFT p99, at
least one scale-up AND one scale-down recorded in the live-scraped
``/fleet``, every chaos event visible in ``/flight``.  A second
scenario drives a ``poison_storm`` through the same harness and
asserts the blast-radius containment contract: every poison ends
terminal QUARANTINED, uncontrolled replica kills stay bounded by
``canary_threshold + 1``, and innocents finish token-identical to a
poison-free oracle.
"""
import dataclasses

import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPT_CONFIGS, gpt_forward, gpt_init
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.slo import SLO, BurnRateAlert
from paddle_tpu.serving import ChaosEvent, Engine, TrafficGenerator, run_soak


def _tiny_cfg():
    return dataclasses.replace(GPT_CONFIGS["tiny"], dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = gpt_init(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


# stable jitted forward — the poison-free greedy oracle (shared jit
# cache: an eager gpt_forward would recompile per call)
_ORACLE_FWD = {}


def naive_generate(cfg, params, prompt, n_new):
    fwd = _ORACLE_FWD.get(id(cfg))
    if fwd is None:
        fwd = _ORACLE_FWD.setdefault(
            id(cfg), jax.jit(lambda p, t: gpt_forward(cfg, p, t)))
    toks = list(prompt)
    for _ in range(n_new):
        logits = fwd(params, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def _engine_factory(tiny_model):
    cfg, params = tiny_model

    def factory():
        # a small queue watermark so the burst actually sheds — the
        # RETRY_AFTER signal is one of the scale-up triggers under test
        return Engine(cfg, params, page_size=8, num_pages=64,
                      max_batch_size=2, chunk_len=8,
                      shed_queue_high=4, shed_queue_low=1)
    return factory


@pytest.mark.faultinject
class TestCompressedSoak:
    def test_chaos_soak_invariants(self, tiny_model):
        traffic = TrafficGenerator(
            base_rate_per_s=6.0, diurnal_amplitude=0.9,
            day_period_s=8.0, phase_s=0.0,
            bursts=((1.0, 2.0, 4.0),),          # spike at t in [1, 3)
            n_cohorts=2, cohort_prefix_len=16, cohort_fraction=0.6,
            prompt_len=(8, 24), max_new_tokens=(4, 6),
            vocab_size=_tiny_cfg().vocab_size, seed=1234)
        chaos = [
            ChaosEvent(t=0.5, action="spawn_io_error"),
            ChaosEvent(t=1.5, action="stall_admit", stall_s=0.4),
            ChaosEvent(t=2.5, action="kill"),
            ChaosEvent(t=3.0, action="stall_poll", stall_s=0.3),
            # one seeded bit flips in a live KV page: silent corruption
            # whose blast radius must be at most one request's output —
            # nothing raises, nobody dies, the accounting stays exact
            ChaosEvent(t=3.5, action="bitflip"),
        ]
        report = run_soak(
            _engine_factory(tiny_model), traffic, horizon_s=8.0,
            initial_replicas=2, chaos=chaos,
            registry=MetricsRegistry(),
            scaler_kw=dict(min_replicas=1, max_replicas=3,
                           up_pressure_s=1.0, down_pressure_s=0.15,
                           up_pending_depth=4,
                           scale_up_cooldown_s=1.5,
                           scale_down_cooldown_s=2.0,
                           spawn_max_retries=2,
                           spawn_backoff_base_s=0.01,
                           spawn_backoff_cap_s=0.05),
            deadline_s=40.0, grace_s=8.0, min_down_events=1,
            ttft_bound_s=25.0)

        # ---- zero loss through kills, stalls, drains, scale events
        assert not report["timed_out"], report
        assert report["requests_submitted"] > 20
        assert report["lost_requests"] == 0, report
        assert report["requests_finished"] == report["requests_submitted"]

        # ---- bounded TTFT p99 (recoveries cost latency, never
        # starvation)
        assert report["ttft_p99_s"] is not None
        assert report["ttft_p99_ok"], report["ttft_p99_s"]

        # ---- elasticity both ways, mid-trace
        events = report["scale_events"]
        assert events.get("up", 0) >= 1, events
        assert events.get("down", 0) >= 1, events
        assert events.get("up", 0) + events.get("down", 0) >= 2

        # ---- the whole kill matrix actually fired
        assert all(ev["action"] in ("kill", "stall_admit", "stall_poll",
                                    "spawn_io_error", "bitflip")
                   for ev in report["chaos"])
        assert len(report["chaos"]) == 5
        fired_sites = {f["site"] for f in report["injector_fired"]}
        assert "serving.admit" in fired_sites
        assert "autoscaler.poll" in fired_sites
        assert "autoscaler.scale_up" in fired_sites
        assert "serving.step" in fired_sites      # the bitflip landed
        # the bitflip corrupted at most one request's *output*, never
        # the fleet: nothing quarantined, no cascade, zero loss above
        assert report["requests_quarantined"] == []
        assert report["fleet"]["cascade_breaker_open"] is False
        # the killed replica's in-flight work was re-dispatched (unless
        # it happened to be idle at kill time — redispatch also comes
        # from drains, so usually > 0)
        assert report["redispatched"] >= 0

        # ---- recoveries visible over live HTTP: /fleet carries the
        # autoscaler block with both directions, /flight the chaos
        # timeline
        scraped = report["scraped"]
        fleet = scraped["fleet"]
        assert fleet["autoscaler"]["scale_events"]["up"] >= 1
        assert fleet["autoscaler"]["scale_events"]["down"] >= 1
        assert fleet["counters"]["lost"] == 0
        flight = scraped["flight"]
        flight_ops = {rec["op"] for rec in flight["records"]}
        flight_ops |= set(flight["summary"]["by_op"])
        soak_ops = {op for op in flight_ops if op.startswith("soak::")}
        assert {"soak::kill", "soak::stall_admit", "soak::stall_poll",
                "soak::spawn_io_error", "soak::bitflip"} <= soak_ops, \
            flight_ops

        # ---- merged fleet trace view over live HTTP: a hard-killed-
        # and-failed-over request reads as ONE trace — one entry per
        # trace_id, the failover hop and both dispatches on it
        traces = scraped["traces"]
        assert traces["fleet"] is True
        merged = traces["traces"]
        tids = [t["trace_id"] for t in merged]
        assert len(tids) == len(set(tids)), "trace split across entries"
        if report["redispatched"]:
            failed_over = [
                t for t in merged
                if any(s["name"] == "router::failover"
                       for s in t["spans"])]
            assert failed_over, "redispatches left no failover trace"
            for t in failed_over:
                names = [s["name"] for s in t["spans"]]
                assert names.count("router::dispatch") >= 2, names
                # tail retention pinned it (failover, or a stronger
                # reason like a fault event recorded on a span)
                assert t["retained"] != "sampled", t["retained"]

    def test_poison_storm_containment(self, tiny_model):
        """The compressed poison-storm scenario: 3 poison requests
        (same query-of-death pattern) land mid-trace on a 3-replica
        fleet with the cascade breaker at K=2.  The containment
        contract, end-to-end through the soak harness:

        - every poison ends terminal QUARANTINED (accounted, not lost);
        - uncontrolled replica kills stay <= K+1 — suspicion pins the
          pattern after 2 kills, the canary trial eats the third, and
          conviction covers the storm's siblings for free;
        - innocents lose nothing and their greedy output is
          token-identical to a poison-free oracle run;
        - the quarantines are visible on the live-scraped ``/fleet``
          and the quarantined traces survive in the tail-retained ring.
        """
        cfg, params = tiny_model
        pattern = (7, 8, 9)
        traffic = TrafficGenerator(
            base_rate_per_s=4.0, diurnal_amplitude=0.5,
            day_period_s=6.0, phase_s=0.0, bursts=(),
            n_cohorts=2, cohort_prefix_len=8, cohort_fraction=0.4,
            prompt_len=(8, 20), max_new_tokens=(4, 6),
            vocab_size=cfg.vocab_size, seed=99)
        chaos = [ChaosEvent(t=1.0, action="poison_storm",
                            pattern=pattern, count=3, max_new_tokens=6)]
        report = run_soak(
            _engine_factory(tiny_model), traffic, horizon_s=6.0,
            initial_replicas=3, chaos=chaos,
            registry=MetricsRegistry(),
            router_kw=dict(canary_threshold=2, cascade_threshold=2,
                           cascade_window_s=2.0),
            scaler_kw=dict(min_replicas=1, max_replicas=3,
                           up_pressure_s=1.0, down_pressure_s=0.15,
                           up_pending_depth=4,
                           scale_up_cooldown_s=1.5,
                           scale_down_cooldown_s=2.0,
                           spawn_max_retries=2,
                           spawn_backoff_base_s=0.01,
                           spawn_backoff_cap_s=0.05),
            deadline_s=40.0, grace_s=8.0, min_down_events=0,
            ttft_bound_s=25.0)

        assert not report["timed_out"], report
        storm_ids = set(report["chaos"][0]["detail"]["request_ids"])
        assert len(storm_ids) == 3

        # ---- every poison terminal QUARANTINED, nothing lost
        assert set(report["requests_quarantined"]) == storm_ids
        assert report["lost_requests"] == 0, report
        assert report["requests_failed"] == []

        # ---- blast radius: <= K+1 uncontrolled kills for the whole
        # storm; the canary death was the controlled one
        counters = report["fleet"]["counters"]
        assert counters["failure_events"] <= 3, counters
        assert counters["canary_deaths"] >= 1
        assert counters["quarantined"] == 3
        assert counters["cascade_breaker_opens"] >= 1

        # ---- innocents: all finished, token-identical to the
        # poison-free oracle (sampled — the oracle recompiles per
        # sequence length, so parity-check a deterministic subset)
        innocents = [r for r in report["requests"]
                     if r["id"] not in storm_ids]
        assert innocents
        assert all(r["state"] == "finished" for r in innocents)
        assert report["requests_finished"] == len(innocents)
        for r in innocents[:6]:
            n_new = len(r["output"])
            assert r["output"] == naive_generate(cfg, params,
                                                 r["prompt"], n_new)

        # ---- containment visible from the outside: /fleet carries
        # the quarantine count, the trace ring retains the verdicts
        scraped = report["scraped"]
        assert scraped["fleet"]["quarantined"] == 3
        assert scraped["fleet"]["counters"]["quarantined"] == 3
        retained = [t for t in scraped["traces"]["traces"]
                    if t.get("retained") == "quarantined"]
        assert len(retained) >= 1, \
            [t.get("retained") for t in scraped["traces"]["traces"]]
        assert any(s["name"] == "router::quarantine"
                   for t in retained for s in t["spans"])

    def test_kill_storm_fires_and_clears_availability_page(
            self, tiny_model):
        """The SLO acceptance scenario: two hard kills mid-trace burn
        the availability error budget at page speed — the fast-burn
        page FIRES during the storm, stays sticky through it, and
        CLEARS through its hysteresis once the fleet recovers, with
        both transitions on the scraped ``/slo`` payload and the
        fire/clear pair pinned in the tail-retained trace ring.  The
        run also asserts the RSS leak-slope query end-to-end (a
        generous bound — the point is the plumbing, not a tight leak
        budget)."""
        traffic = TrafficGenerator(
            base_rate_per_s=6.0, diurnal_amplitude=0.3,
            day_period_s=8.0, phase_s=0.0, bursts=(),
            n_cohorts=2, cohort_prefix_len=8, cohort_fraction=0.4,
            prompt_len=(8, 16), max_new_tokens=(4, 6),
            vocab_size=_tiny_cfg().vocab_size, seed=4321)
        chaos = [ChaosEvent(t=1.5, action="kill"),
                 ChaosEvent(t=2.4, action="kill")]
        # availability over router counters: uncontrolled replica
        # failures + lost requests per dispatch.  target 0.99 makes a
        # single kill in the window burn ~10-20x budget (failures are
        # a few percent of dispatches), so threshold 2 fires reliably
        # on BOTH windows during the storm and reads 0 outside it.
        slos = (SLO(
            "fleet_availability", target=0.99,
            bad=("router_replica_failure_events_total",
                 "router_requests_lost_total"),
            total=("router_dispatches_total",),
            alerts=(BurnRateAlert("page", burn_rate_threshold=2.0,
                                  long_window_seconds=3.0,
                                  short_window_seconds=1.0,
                                  clear_after_seconds=0.75),),
            budget_window_seconds=30.0),)
        report = run_soak(
            _engine_factory(tiny_model), traffic, horizon_s=6.0,
            initial_replicas=2, chaos=chaos,
            registry=MetricsRegistry(), slos=slos,
            scaler_kw=dict(min_replicas=1, max_replicas=3,
                           up_pressure_s=1.0, down_pressure_s=0.15,
                           up_pending_depth=4,
                           scale_up_cooldown_s=1.5,
                           scale_down_cooldown_s=2.0,
                           spawn_max_retries=2,
                           spawn_backoff_base_s=0.01,
                           spawn_backoff_cap_s=0.05),
            deadline_s=40.0, grace_s=8.0, min_down_events=0,
            ttft_bound_s=25.0,
            rss_slope_bound_bytes_per_s=256e6)

        assert not report["timed_out"], report
        assert report["lost_requests"] == 0, report

        # ---- the page fired during the storm and cleared after it
        slo_report = report["slo"]
        kinds = [t["transition"]
                 for t in slo_report["transitions"]
                 if t["slo"] == "fleet_availability"]
        assert "fire" in kinds and "clear" in kinds, slo_report
        assert kinds[0] == "fire" and kinds[-1] == "clear"
        (alert,) = slo_report["slos"]["fleet_availability"]["alerts"]
        assert alert["fired"] >= 1
        assert alert["active"] is False           # hysteresis ran out
        assert slo_report["page_active"] is False

        # ---- both transitions visible on the live-scraped /slo
        scraped = report["scraped"]
        scraped_kinds = [t["transition"]
                         for t in scraped["slo"]["transitions"]]
        assert "fire" in scraped_kinds and "clear" in scraped_kinds
        assert scraped["slo"]["page_active"] is False
        # the page un-degraded /healthz again by scrape time
        assert scraped["healthz"]["slo_page_active"] is False

        # ---- fire/clear pair pinned in the tail-retained trace ring
        slo_traces = [t for t in scraped["traces"]["traces"]
                      if t["name"] == "slo::fleet_availability"]
        trace_kinds = {t["spans"][0]["attributes"]["transition"]
                       for t in slo_traces}
        assert {"fire", "clear"} <= trace_kinds, \
            [t.get("retained") for t in scraped["traces"]["traces"]]
        assert all(t["retained"] == "flagged" for t in slo_traces)

        # ---- windowed store ran all run long and the leak-slope
        # query answered (S2: ResourceSampler gauges -> slope)
        assert report["timeseries"]["scrapes"] > 10
        assert report["rss_slope_bytes_per_s"] is not None
        assert report["rss_slope_ok"] is True, \
            report["rss_slope_bytes_per_s"]
        assert scraped["timeseries"]["series"] > 0

    def test_page_arms_profile_capture_and_load_backs_off(
            self, tiny_model):
        """Continuous-profiling + closed-loop acceptance on the kill
        storm: the firing availability page arms a high-rate stack
        capture that lands in the retained set LINKED to the firing
        ``slo::`` transition's trace (same trace_id, ``flagged``
        retention), the live ``/profilez`` scrape answers with phase
        slices that sum to the sampled wall time, and with
        ``burn_feedback=True`` the generator thins submissions while
        the page burns — load measurably backs off, and thinned
        arrivals are accounted as feedback drops, never as lost."""
        traffic = TrafficGenerator(
            base_rate_per_s=6.0, diurnal_amplitude=0.3,
            day_period_s=8.0, phase_s=0.0, bursts=(),
            n_cohorts=2, cohort_prefix_len=8, cohort_fraction=0.4,
            prompt_len=(8, 16), max_new_tokens=(4, 6),
            vocab_size=_tiny_cfg().vocab_size, seed=4321)
        chaos = [ChaosEvent(t=1.5, action="kill"),
                 ChaosEvent(t=2.4, action="kill")]
        slos = (SLO(
            "fleet_availability", target=0.99,
            bad=("router_replica_failure_events_total",
                 "router_requests_lost_total"),
            total=("router_dispatches_total",),
            # wider windows than the bare SLO scenario: a respawn can
            # stall the driver loop (and its scrape cadence) for ~1s,
            # and a short window narrower than the stall never sees
            # the failure bump and its dispatch denominator together
            alerts=(BurnRateAlert("page", burn_rate_threshold=2.0,
                                  long_window_seconds=4.0,
                                  short_window_seconds=2.0,
                                  clear_after_seconds=0.75),),
            budget_window_seconds=30.0),)
        report = run_soak(
            _engine_factory(tiny_model), traffic, horizon_s=6.0,
            initial_replicas=2, chaos=chaos,
            registry=MetricsRegistry(), slos=slos,
            burn_feedback=True,
            scaler_kw=dict(min_replicas=1, max_replicas=3,
                           up_pressure_s=1.0, down_pressure_s=0.15,
                           up_pending_depth=4,
                           scale_up_cooldown_s=1.5,
                           scale_down_cooldown_s=2.0,
                           spawn_max_retries=2,
                           spawn_backoff_base_s=0.01,
                           spawn_backoff_cap_s=0.05),
            deadline_s=40.0, grace_s=8.0, min_down_events=0,
            ttft_bound_s=25.0)

        assert not report["timed_out"], report
        assert report["lost_requests"] == 0, report
        kinds = [t["transition"] for t in report["slo"]["transitions"]
                 if t["slo"] == "fleet_availability"]
        assert "fire" in kinds, report["slo"]

        # ---- the page armed a capture; it finished and was retained
        prof = report["profiling"]
        assert prof["stats"]["lifetime_samples"] > 0
        caps = [c for c in prof["captures"]
                if c["trigger"] == "slo_page"]
        assert caps, prof
        cap = caps[0]
        assert cap["detail"] == "fleet_availability"
        assert cap["samples"] > 0 and cap["hot"], cap

        # ---- linked to the firing slo:: transition: the capture span
        # CONTINUES that trace, so the merged ring shows one flagged
        # trace carrying both spans
        scraped = report["scraped"]
        linked = [t for t in scraped["traces"]["traces"]
                  if any(s["name"] == "profiling::capture"
                         for s in t["spans"])]
        assert linked, "capture span missing from the retained ring"
        (tr,) = [t for t in linked if t["trace_id"] == cap["trace_id"]]
        span_names = [s["name"] for s in tr["spans"]]
        assert "slo::fleet_availability" in span_names, span_names
        assert tr["retained"] == "flagged"

        # ---- /profilez answered live; phase slices sum to wall time
        pz = scraped["profilez"]
        assert pz["samples"] > 0
        assert abs(sum(v["seconds"] for v in pz["by_phase"].values())
                   - pz["sampled_seconds"]) < 1e-6
        assert any(c["trigger"] == "slo_page" for c in pz["captures"])

        # ---- closed loop: load backed off while the page burned
        bf = report["burn_feedback"]
        assert bf["enabled"] is True
        assert bf["dropped"] >= bf["dropped_while_page"] >= 1, bf
