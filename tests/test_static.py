"""Static Program/Executor tests (reference strategy: build a program
with static.data + layers under program_guard, run via Executor with
feeds, compare against dygraph — plus the pass framework)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import Executor, Program, data, new_pass, program_guard


class TestProgramCapture:
    def test_build_and_run(self):
        paddle.seed(0)
        main = Program()
        with program_guard(main):
            x = data("x", [None, 8], "float32")
            net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                nn.Linear(16, 4))
            y = net(x)
        assert len(main.ops) >= 3
        exe = Executor()
        arr = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        (out,) = exe.run(main, feed={"x": arr}, fetch_list=[y])
        ref = np.asarray(net(paddle.to_tensor(arr)).data)
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_feed_shape_polymorphism(self):
        """The None batch dim accepts different batch sizes at run time."""
        paddle.seed(1)
        main = Program()
        with program_guard(main):
            x = data("x", [None, 4], "float32")
            lin = nn.Linear(4, 2)
            y = lin(x)
        exe = Executor()
        for bs in (2, 7):
            arr = np.ones((bs, 4), np.float32)
            (out,) = exe.run(main, feed={"x": arr}, fetch_list=[y])
            assert out.shape == (bs, 2)

    def test_param_updates_visible(self):
        """Captured parameters are live references: mutating the layer's
        weights changes subsequent runs (the scope-variable semantics)."""
        paddle.seed(2)
        main = Program()
        with program_guard(main):
            x = data("x", [None, 3], "float32")
            lin = nn.Linear(3, 3)
            y = lin(x)
        exe = Executor()
        arr = np.eye(3, dtype=np.float32)
        (before,) = exe.run(main, feed={"x": arr}, fetch_list=[y])
        lin.weight.data = lin.weight.data * 2.0
        lin.bias.data = lin.bias.data * 2.0
        (after,) = exe.run(main, feed={"x": arr}, fetch_list=[y])
        np.testing.assert_allclose(after, before * 2.0, atol=1e-5)

    def test_program_to_string(self):
        main = Program()
        with program_guard(main):
            x = data("x", [None, 4], "float32")
            y = paddle.ops.relu(x)
        s = str(main)
        assert "feed x" in s and "relu" in s

    def test_multiple_fetches(self):
        main = Program()
        with program_guard(main):
            x = data("x", [None, 4], "float32")
            a = paddle.ops.relu(x)
            b = paddle.ops.exp(x)
        exe = Executor()
        arr = np.array([[-1.0, 0.0, 1.0, 2.0]], np.float32)
        out_a, out_b = exe.run(main, feed={"x": arr}, fetch_list=[a, b])
        np.testing.assert_allclose(out_a, np.maximum(arr, 0), atol=1e-6)
        np.testing.assert_allclose(out_b, np.exp(arr), atol=1e-5)


class TestPasses:
    def test_dead_code_elimination(self):
        main = Program()
        with program_guard(main):
            x = data("x", [None, 4], "float32")
            live = paddle.ops.relu(x)
            _dead = paddle.ops.exp(x)       # never fetched
            _dead2 = paddle.ops.tanh(_dead)
        prog = main.clone()
        removed = new_pass("dead_code_elimination").apply(
            prog, [main.lookup(live)])
        assert removed == 2
        assert [op.name for op in prog.ops] == ["relu"]
        # and the executor (which runs DCE by default) still computes right
        exe = Executor()
        arr = np.array([[-2.0, 3.0, 0.0, 1.0]], np.float32)
        (out,) = exe.run(main, feed={"x": arr}, fetch_list=[live])
        np.testing.assert_allclose(out, np.maximum(arr, 0), atol=1e-6)

    def test_amp_bf16_pass(self):
        paddle.seed(3)
        main = Program()
        with program_guard(main):
            x = data("x", [None, 16], "float32")
            lin = nn.Linear(16, 16)
            y = lin(x)
        prog = main.clone()
        n = new_pass("amp_bf16").apply(prog, [main.lookup(y)])
        assert n >= 1                     # the matmul got wrapped
        arr = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        fp32 = prog.replay({"x": arr}, [main.lookup(y)])[0]
        assert fp32.dtype == np.float32   # restored output dtype
        ref = np.asarray(lin(paddle.to_tensor(arr)).data)
        # bf16 compute: close but not identical
        np.testing.assert_allclose(np.asarray(fp32), ref, atol=0.1)
        assert np.abs(np.asarray(fp32) - ref).max() > 0   # really bf16

    def test_pass_registry(self):
        from paddle_tpu.static.passes import PASS_REGISTRY

        assert "dead_code_elimination" in PASS_REGISTRY
        assert "amp_bf16" in PASS_REGISTRY
