"""Self-tests for the unified static-analysis framework (tools/analysis).

Covers, per ISSUE 11:

- core mechanics: one-parse project loader, ``# lint-ok`` suppression
  (same line + comment block above), per-rule baseline files
  (write/load/removal, line-number independence), the runner report
  and the CLI;
- the lock-discipline race detector on fixture snippets: guarded
  access in/out of a lock region (``with``, ``acquire/release``,
  ``_locked`` contract, ``__init__`` exemption, mutator calls,
  module globals, closure locals), a lock-order cycle, and a split
  check-then-act — including the acceptance fixture proving all three
  are invisible to the six legacy lints;
- the JAX trace-purity pass on fixture snippets: clocks, host
  randomness, host-sync forcers, global/attribute mutation and
  ``print`` inside jitted call graphs, with the static-shape and
  unreached-function true negatives;
- the tier-1 wiring: one suite run over the real repo must be clean
  and under the 10s budget (this test IS the consolidated tier-1
  entry replacing the six per-lint repo sweeps);
- targeted regressions for the real races this PR's annotation sweep
  surfaced and fixed (flight note_step torn pair, tracer summary torn
  read, aggregator torn fleet state, flags registry reads, resource
  sampler, checkpoint-manager error handoff).

Extended per ISSUE 13 with the SPMD collective-discipline matrix
(rank-conditional hang / order divergence / sanctioned ``# rank-ok``
protocols / unbounded distributed waits), the sharding-spec matrix
(unknown/duplicate axes, donate arity, dead rules), the
``--changed-only`` CLI scope, and the fleet-router lock regression
from the guarded-by sweep.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analysis.core import (REGISTRY, Finding, Project,  # noqa: E402
                                 apply_suppressions, changed_files,
                                 load_baseline, main, run_all, run_pass,
                                 write_baseline)
from tools.analysis import passes as _passes  # noqa: E402,F401  (registers)
from tools.analysis.passes import (collective_discipline,  # noqa: E402
                                   lock_discipline, sharding_spec,
                                   trace_purity)

ALL_RULES = {"atomic-writes", "metric-names", "fault-sites",
             "collective-instrumented", "bounded-retries", "excepts",
             "lock-discipline", "trace-purity", "span-discipline",
             "collective-discipline", "sharding-spec"}

LEGACY_RULES = ALL_RULES - {"lock-discipline", "trace-purity",
                            "span-discipline",
                            "collective-discipline", "sharding-spec"}


def _project(tmp_path, files):
    """Build a fixture package tree and return a Project over it."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(package_root=str(pkg),
                   tests_root=str(tmp_path / "tests"))


def _findings(rule, project):
    return apply_suppressions(project, REGISTRY[rule](project))


def _assert_needs_lock(lock, fn, what):
    """Deterministic lockedness probe: with ``lock`` held externally,
    ``fn`` must block; releasing must let it finish."""
    done = threading.Event()

    def run():
        fn()
        done.set()

    lock.acquire()
    try:
        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert not done.wait(0.2), \
            f"{what} completed while its lock was held — unguarded access"
    finally:
        lock.release()
    assert done.wait(5.0), f"{what} never completed after lock release"
    t.join(timeout=5.0)


# ===================================================================== core

class TestCore:
    def test_modules_parsed_once_and_cached(self, tmp_path):
        p = _project(tmp_path, {"a.py": "x = 1\n", "sub/b.py": "y = 2\n"})
        mods = p.modules()
        assert [m.rel for m in mods] == ["pkg/a.py", "pkg/sub/b.py"]
        assert p.modules() is mods                  # cached, not re-walked
        tree = mods[0].tree
        assert mods[0].tree is tree                 # one parse per file

    def test_syntax_error_file_is_skipped_not_fatal(self, tmp_path):
        p = _project(tmp_path, {"broken.py": "def f(:\n"})
        assert p.modules()[0].tree is None
        # every pass must survive an unparseable file
        report = run_all(p, baseline_dir=str(tmp_path / "bl"))
        assert set(report["passes"]) == ALL_RULES

    def test_suppression_same_line_and_comment_block_above(self, tmp_path):
        src = """\
        import threading
        _LOCK = threading.Lock()
        _CACHE = {}     # guarded-by: _LOCK

        def same_line(k):
            return _CACHE.get(k)    # lint-ok: lock-discipline vetted

        def line_above(k):
            # a longer explanation of why this read is safe
            # lint-ok: lock-discipline vetted read
            return _CACHE.get(k)

        def naked_marker(k):
            return _CACHE.get(k)    # lint-ok: lock-discipline
        """
        p = _project(tmp_path, {"m.py": src})
        flagged = _findings("lock-discipline", p)
        # the reason-less marker suppresses nothing; the other two do
        assert len(flagged) == 1
        assert "naked_marker" in flagged[0].message

    def test_baseline_roundtrip_and_removal(self, tmp_path):
        bl = str(tmp_path / "bl")
        f1 = Finding("pkg/a.py", 10, "excepts", "bad thing")
        f2 = Finding("pkg/b.py", 20, "excepts", "other thing")
        write_baseline("excepts", [f1, f2], baseline_dir=bl)
        keys = load_baseline("excepts", baseline_dir=bl)
        assert keys == {f1.baseline_key, f2.baseline_key}
        # line numbers are NOT part of the key: an unrelated edit that
        # shifts the finding must stay grandfathered
        moved = Finding("pkg/a.py", 99, "excepts", "bad thing")
        assert moved.baseline_key in keys
        # empty regeneration removes the file
        write_baseline("excepts", [], baseline_dir=bl)
        assert not os.path.exists(os.path.join(bl, "excepts.txt"))
        assert load_baseline("excepts", baseline_dir=bl) == set()

    def test_run_pass_splits_new_vs_baselined(self, tmp_path):
        src = """\
        def f():
            try:
                pass
            except Exception:
                pass
        """
        p = _project(tmp_path, {"m.py": src})
        bl = str(tmp_path / "bl")
        fn = REGISTRY["excepts"]
        new, old, _ = run_pass(fn, p, baseline_dir=bl)
        assert len(new) == 1 and old == []
        write_baseline("excepts", new, baseline_dir=bl)
        new2, old2, _ = run_pass(fn, p, baseline_dir=bl)
        assert new2 == [] and len(old2) == 1

    def test_cli_list_and_fixture_run(self, tmp_path, capsys):
        assert main(["--list"]) == 0
        out = capsys.readouterr().out
        for rule in ALL_RULES:
            assert rule in out
        # clean fixture -> 0; dirty fixture -> 1 with the finding printed
        clean = tmp_path / "clean"
        clean.mkdir()
        (clean / "ok.py").write_text("x = 1\n")
        assert main(["--root", str(clean)]) == 0
        dirty = tmp_path / "dirty"
        dirty.mkdir()
        (dirty / "bad.py").write_text(
            "try:\n    pass\nexcept Exception:\n    pass\n")
        assert main(["--root", str(dirty), "--rule", "excepts"]) == 1
        err = capsys.readouterr().err
        assert "excepts" in err and "bad.py" in err

    def test_all_ten_passes_registered(self):
        assert set(REGISTRY) == ALL_RULES


# ========================================================== lock-discipline

_RING_FIXTURE = """\
import threading


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = []         # guarded-by: self._lock
        self._n = 0             # guarded-by: self._lock

    def ok_with(self, x):
        with self._lock:
            self._ring.append(x)
            self._n += 1

    def ok_acquire_release(self, x):
        self._lock.acquire()
        try:
            self._ring.append(x)
        finally:
            self._lock.release()

    def flush_locked(self):
        self._ring.clear()
        self._n = 0

    def bad_write(self, x):
        self._ring.append(x)

    def bad_read(self):
        return len(self._ring)
"""


class TestLockDiscipline:
    def test_guarded_access_in_and_out_of_lock_region(self, tmp_path):
        p = _project(tmp_path, {"ring.py": _RING_FIXTURE})
        flagged = _findings("lock-discipline", p)
        msgs = [f.message for f in flagged]
        # only the two bad_* methods fire: with-region, acquire/release
        # span, the _locked caller contract and __init__ are all clean
        assert len(flagged) == 2, msgs
        assert any("bad_write" in m and "write" in m for m in msgs)
        assert any("bad_read" in m and "read" in m for m in msgs)

    def test_lock_order_cycle_detected(self, tmp_path):
        src = """\
        import threading


        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._x = 0     # guarded-by: self._a
                self._y = 0     # guarded-by: self._b

            def ab(self):
                with self._a:
                    with self._b:
                        self._y = 1

            def ba(self):
                with self._b:
                    with self._a:
                        self._x = 2
        """
        p = _project(tmp_path, {"locks.py": src})
        flagged = _findings("lock-discipline", p)
        cyc = [f for f in flagged if "lock-order cycle" in f.message]
        assert len(cyc) == 1
        assert "TwoLocks._a" in cyc[0].message
        assert "TwoLocks._b" in cyc[0].message

    def test_consistent_order_has_no_cycle(self, tmp_path):
        src = """\
        import threading


        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._x = 0     # guarded-by: self._a
                self._y = 0     # guarded-by: self._b

            def one(self):
                with self._a:
                    with self._b:
                        self._y = 1

            def two(self):
                with self._a:
                    with self._b:
                        self._y = 2
                        self._x = 3
        """
        p = _project(tmp_path, {"locks.py": src})
        assert _findings("lock-discipline", p) == []

    def test_split_check_then_act_detected(self, tmp_path):
        src = """\
        import threading


        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0     # guarded-by: self._lock

            def bad_take(self):
                with self._lock:
                    ready = self._n > 0
                work = ready
                with self._lock:
                    self._n -= 1
                return work

            def ok_take(self):
                with self._lock:
                    if self._n > 0:
                        self._n -= 1
                        return True
                    return False
        """
        p = _project(tmp_path, {"pool.py": src})
        flagged = _findings("lock-discipline", p)
        assert len(flagged) == 1
        f = flagged[0]
        assert "split check-then-act" in f.message
        assert "bad_take" in f.message and "_n" in f.message

    def test_module_global_guard(self, tmp_path):
        src = """\
        import threading

        _LOCK = threading.Lock()
        _REGISTRY = {}      # guarded-by: _LOCK


        def ok_put(k, v):
            with _LOCK:
                _REGISTRY[k] = v


        def bad_get(k):
            return _REGISTRY.get(k)
        """
        p = _project(tmp_path, {"reg.py": src})
        flagged = _findings("lock-discipline", p)
        assert len(flagged) == 1
        assert "bad_get" in flagged[0].message
        assert "_REGISTRY" in flagged[0].message

    def test_closure_local_guard(self, tmp_path):
        # the dataloader worker idiom: results dict shared with worker
        # threads, declared in the enclosing function
        src = """\
        import threading


        def pipeline(batches):
            cond = threading.Condition()
            results = {}    # guarded-by: cond

            def worker(i, batch):
                with cond:
                    results[i] = batch
                    cond.notify_all()

            def bad_drain(i):
                return results.pop(i)

            return worker, bad_drain
        """
        p = _project(tmp_path, {"dl.py": src})
        flagged = _findings("lock-discipline", p)
        assert len(flagged) == 1
        assert "bad_drain" in flagged[0].message

    def test_suppression_applies(self, tmp_path):
        src = _RING_FIXTURE.replace(
            "        self._ring.append(x)\n\n    def bad_read",
            "        self._ring.append(x)  "
            "# lint-ok: lock-discipline single-writer by contract\n\n"
            "    def bad_read")
        p = _project(tmp_path, {"ring.py": src})
        flagged = _findings("lock-discipline", p)
        assert len(flagged) == 1 and "bad_read" in flagged[0].message

    def test_acceptance_invisible_to_legacy_lints(self, tmp_path):
        """ISSUE 11 acceptance: an unguarded write, a lock-order cycle
        and a split check-then-act in ONE fixture — the race detector
        catches all three; none of the six migrated legacy lints sees
        anything."""
        src = """\
        import threading


        class Hazard:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._jobs = []     # guarded-by: self._a
                self._done = 0      # guarded-by: self._b

            def unguarded_write(self, j):
                self._jobs.append(j)

            def order_ab(self):
                with self._a:
                    with self._b:
                        self._done += 1

            def order_ba(self):
                with self._b:
                    with self._a:
                        self._jobs.pop()

            def split_cta(self):
                with self._b:
                    pending = self._done < 10
                if pending:
                    with self._b:
                        self._done += 1
        """
        p = _project(tmp_path, {"hazard.py": src})
        race = _findings("lock-discipline", p)
        kinds = "\n".join(f.message for f in race)
        assert "unguarded write" in kinds
        assert "lock-order cycle" in kinds
        assert "split check-then-act" in kinds
        for rule in LEGACY_RULES:
            assert _findings(rule, p) == [], \
                f"legacy lint {rule} unexpectedly fired on the fixture"


# ============================================================ trace-purity

class TestTracePurity:
    def test_clock_read_in_decorated_jit(self, tmp_path):
        src = """\
        import time
        import jax


        @jax.jit
        def step(x):
            return x + time.time()
        """
        p = _project(tmp_path, {"m.py": src})
        flagged = _findings("trace-purity", p)
        assert len(flagged) == 1
        assert "wall-clock" in flagged[0].message
        assert "step()" in flagged[0].message

    def test_clean_jitted_fn_and_unreached_impurity(self, tmp_path):
        src = """\
        import time
        import jax


        @jax.jit
        def step(x):
            return x * 2


        def host_only():
            return time.time()
        """
        p = _project(tmp_path, {"m.py": src})
        assert _findings("trace-purity", p) == []

    def test_impurity_reached_through_call_graph(self, tmp_path):
        src = """\
        import random
        import jax


        def _noise():
            return random.random()


        def step(x):
            return x + _noise()


        step_jit = jax.jit(step)
        """
        p = _project(tmp_path, {"m.py": src})
        flagged = _findings("trace-purity", p)
        assert len(flagged) == 1
        assert "host randomness" in flagged[0].message
        assert "_noise()" in flagged[0].message

    def test_cross_module_call_graph(self, tmp_path):
        files = {
            "util.py": """\
            import os


            def pick_kernel():
                return os.getenv("KERNEL")
            """,
            "main.py": """\
            import jax

            from pkg.util import pick_kernel


            @jax.jit
            def step(x):
                k = pick_kernel()
                return x
            """,
        }
        p = _project(tmp_path, files)
        flagged = _findings("trace-purity", p)
        assert len(flagged) == 1
        assert flagged[0].file == "pkg/util.py"
        assert "environment read" in flagged[0].message

    def test_host_sync_forcers_and_static_exemptions(self, tmp_path):
        src = """\
        import numpy as np
        import jax


        @jax.jit
        def bad_item(x):
            return x.item()


        @jax.jit
        def bad_float(x):
            return float(x)


        @jax.jit
        def bad_asarray(x):
            return np.asarray(x)


        @jax.jit
        def ok_static(x):
            d = float(x.shape[0])
            n = int(x.ndim)
            return x * d * n
        """
        p = _project(tmp_path, {"m.py": src})
        flagged = _findings("trace-purity", p)
        by_fn = {f.message.split("reached via ")[1][:-1]: f.message
                 for f in flagged}
        assert len(flagged) == 3, sorted(by_fn)
        assert "'.item()'" in by_fn["bad_item()"]
        assert "'float(...)'" in by_fn["bad_float()"]
        assert "host sync" in by_fn["bad_asarray()"]
        assert not any("ok_static" in k for k in by_fn)

    def test_state_mutation_and_print(self, tmp_path):
        src = """\
        import jax

        _CACHE = {}


        @jax.jit
        def bad_global(x):
            global _COUNT
            _COUNT = 1
            return x


        @jax.jit
        def bad_attr(cfg, x):
            cfg.calls = 1
            return x


        @jax.jit
        def bad_cache_read(x):
            return x if _CACHE else x


        @jax.jit
        def chatty(x):
            print(x)
            return x
        """
        p = _project(tmp_path, {"m.py": src})
        msgs = "\n".join(f.message for f in _findings("trace-purity", p))
        assert "'global' mutation" in msgs
        assert "attribute store" in msgs
        assert "module-global mutable state '_CACHE'" in msgs
        assert "print" in msgs

    def test_suppression_applies(self, tmp_path):
        src = """\
        import time
        import jax


        @jax.jit
        def step(x):
            # lint-ok: trace-purity the timestamp is a compile stamp
            t = time.time()
            return x + t
        """
        p = _project(tmp_path, {"m.py": src})
        assert _findings("trace-purity", p) == []

    def test_repo_call_graph_is_nonempty(self):
        """The pass must actually reach the repo's jitted step functions
        — an empty reach set would make the clean suite vacuous."""
        reached = trace_purity.traced_functions(Project())
        assert len(reached) >= 10
        blob = "\n".join(reached)
        assert "models/gpt.py" in blob


# ========================================================= span-discipline

class TestSpanDiscipline:
    def test_discarded_start_call_flagged(self, tmp_path):
        src = """\
        def handle(tracer):
            tracer.start_trace("req")
            return 1
        """
        p = _project(tmp_path, {"m.py": src})
        flagged = _findings("span-discipline", p)
        assert len(flagged) == 1
        assert "discarded" in flagged[0].message
        assert "handle()" in flagged[0].message

    def test_chained_end_and_mutator_chain_ok(self, tmp_path):
        src = """\
        def zero_width(tracer, now):
            tracer.start_span("evt", None, start_s=now).end(now)

        def via_mutator(tracer, now):
            tracer.start_trace("evt").set_attribute("k", 1).end(now)
        """
        p = _project(tmp_path, {"m.py": src})
        assert _findings("span-discipline", p) == []

    def test_with_statement_and_escapes_ok(self, tmp_path):
        src = """\
        def ctx(tracer):
            with tracer.start_trace("req") as span:
                span.set_attribute("k", 1)

        def stored(tracer, req):
            req._span = tracer.start_trace("req")

        def returned(tracer):
            return tracer.start_trace("req")

        def handed_off(tracer, sink):
            span = tracer.start_trace("req")
            sink(span)

        def packed(tracer, out):
            span = tracer.start_trace("req")
            out.append(span)
        """
        p = _project(tmp_path, {"m.py": src})
        assert _findings("span-discipline", p) == []

    def test_local_never_ended_flagged(self, tmp_path):
        src = """\
        def leak(tracer):
            span = tracer.start_trace("req")
            span.set_attribute("k", 1)
            return 1
        """
        p = _project(tmp_path, {"m.py": src})
        flagged = _findings("span-discipline", p)
        assert len(flagged) == 1
        assert "span 'span'" in flagged[0].message
        assert "return with span open" in flagged[0].message
        assert flagged[0].line == 2

    def test_return_on_one_branch_while_open_flagged(self, tmp_path):
        src = """\
        def race(tracer, fast):
            span = tracer.start_trace("req")
            if fast:
                return 0
            span.end()
            return 1
        """
        p = _project(tmp_path, {"m.py": src})
        flagged = _findings("span-discipline", p)
        assert len(flagged) == 1
        assert "return with span open (line 4)" in flagged[0].message

    def test_all_branches_end_ok(self, tmp_path):
        src = """\
        def branchy(tracer, ok):
            span = tracer.start_trace("req")
            if ok:
                span.set_attribute("outcome", "ok")
                span.end()
            else:
                span.set_attribute("outcome", "bad")
                span.end()
            return 1
        """
        p = _project(tmp_path, {"m.py": src})
        assert _findings("span-discipline", p) == []

    def test_try_finally_end_covers_raise_paths(self, tmp_path):
        src = """\
        def guarded(tracer, work):
            span = tracer.start_trace("req")
            try:
                work()
            finally:
                span.end()
            return 1
        """
        p = _project(tmp_path, {"m.py": src})
        assert _findings("span-discipline", p) == []

    def test_fallthrough_open_flagged_and_suppression(self, tmp_path):
        src = """\
        def drops(tracer):
            span = tracer.start_trace("req")
            span.set_attribute("k", 1)

        def vetted(tracer):
            # lint-ok: span-discipline force-ended by root end at exit
            span = tracer.start_trace("req")
            span.set_attribute("k", 1)
        """
        p = _project(tmp_path, {"m.py": src})
        flagged = _findings("span-discipline", p)
        assert len(flagged) == 1
        assert "fallthrough with span open" in flagged[0].message
        assert flagged[0].line == 2

    def test_nested_function_is_its_own_unit(self, tmp_path):
        src = """\
        def outer(tracer):
            def inner():
                s = tracer.start_trace("inner")
                s.end()
            return inner
        """
        p = _project(tmp_path, {"m.py": src})
        assert _findings("span-discipline", p) == []

    def test_repo_is_clean(self):
        flagged = apply_suppressions(
            Project(), REGISTRY["span-discipline"](Project()))
        assert flagged == [], "\n".join(str(f) for f in flagged)


# ===================================================== migrated lint shims

class TestMigratedShims:
    """The six legacy lints now live on the shared core; their old
    module paths stay importable with the old ``check()`` surface (the
    deep behavioral self-tests live with their original features)."""

    SHIMS = ["check_atomic_writes", "check_metric_names",
             "check_fault_sites", "check_collective_instrumented",
             "check_bounded_retries", "check_excepts"]

    def test_shims_expose_legacy_check_surface(self):
        import importlib.util

        for name in self.SHIMS:
            spec = importlib.util.spec_from_file_location(
                name, os.path.join(REPO, "tools", f"{name}.py"))
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            assert callable(mod.check), name
            assert callable(mod.main), name

    def test_legacy_rules_ride_the_shared_project(self, tmp_path):
        # one Project, all legacy passes: the point of the migration is
        # a single parse, so every pass must accept the same instance
        p = _project(tmp_path, {"m.py": "x = 1\n"})
        for rule in LEGACY_RULES:
            assert _findings(rule, p) == [], rule


# ====================================== SLO/alert identifier discipline

class TestSLONamingLint:
    """The metric-names pass extended to SLO/BurnRateAlert
    declarations: snake_case slo names, spelled-out ``_seconds``
    kwargs, severities from the fixed enum."""

    def test_planted_violations_caught(self, tmp_path):
        p = _project(tmp_path, {"slos.py": """\
            from paddle_tpu.observability.slo import SLO, BurnRateAlert

            a = SLO('TTFT-Fast', target=0.99, bad='b_total',
                    total='t_total')                       # not snake
            b = SLO(name='Bad Name', target=0.9, bad='b_total',
                    total='t_total')                       # kwarg form
            c = BurnRateAlert('warning', burn_rate_threshold=1.0,
                              long_window_seconds=60.0,
                              short_window_seconds=5.0)    # bad enum
            d = BurnRateAlert(severity='critical',
                              burn_rate_threshold=1.0,
                              long_window_seconds=60.0,
                              short_window_seconds=5.0)    # bad enum
            e = BurnRateAlert('page', burn_rate_threshold=1.0,
                              long_window_s=60.0,
                              short_window_seconds=5.0)    # _s kwarg
            f = SLO('ok_name', target=0.9, bad='b_total',
                    total='t_total', budget_window_ms=9.0)  # _ms kwarg
            """})
        text = "\n".join(f.message
                         for f in _findings("metric-names", p))
        assert "'TTFT-Fast' is not snake_case" in text
        assert "'Bad Name' is not snake_case" in text
        assert "'warning' is not in the fixed enum" in text
        assert "'critical' is not in the fixed enum" in text
        assert "'long_window_s' abbreviates" in text
        assert "'budget_window_ms' abbreviates" in text
        assert len(_findings("metric-names", p)) == 6

    def test_clean_declarations_pass(self, tmp_path):
        p = _project(tmp_path, {"slos.py": """\
            from paddle_tpu.observability.slo import SLO, BurnRateAlert

            a = SLO('availability', target=0.999,
                    bad=('shed_total',), total=('req_total',),
                    alerts=(BurnRateAlert(
                        'page', burn_rate_threshold=14.4,
                        long_window_seconds=60.0,
                        short_window_seconds=5.0,
                        clear_after_seconds=5.0),),
                    budget_window_seconds=3600.0)
            sev = pick_severity()
            b = BurnRateAlert(sev, burn_rate_threshold=3.0,
                              long_window_seconds=300.0,
                              short_window_seconds=30.0)  # variable: skip
            """})
        assert _findings("metric-names", p) == []

    def test_unpinned_profiling_series_detected(self, tmp_path):
        p = _project(tmp_path, {"prof.py": """\
            c = reg.counter('profiling_samples_total', 'pinned: ok')
            d = reg.counter('profiling_bogus_total', 'not pinned')
            """})
        out = _findings("metric-names", p)
        assert len(out) == 1
        assert "profiling_bogus_total" in out[0].message
        assert "pinned" in out[0].message

    def test_severity_enum_stays_in_sync_with_package(self):
        """The pass pins the enum (it must not import the package it
        analyses); this is the sync check its comment promises."""
        from paddle_tpu.observability.slo import SEVERITIES
        from tools.analysis.passes import metric_names

        assert tuple(metric_names._SEVERITIES) == tuple(SEVERITIES)

    def test_repo_slo_declarations_clean(self):
        """The real tree's SLO/alert declarations (soak harness, bench
        fixtures under paddle_tpu/) satisfy the extended rules."""
        out = [f for f in _findings("metric-names", Project())
               if "slo" in f.message.lower()
               or "alert" in f.message.lower()]
        assert out == []


# ================================================== tier-1 suite + budget

class TestTier1Suite:
    def test_repo_clean_and_under_budget(self):
        """THE consolidated tier-1 entry: every pass over the real repo,
        zero unbaselined findings, inside the 10s budget (the six
        per-lint repo sweeps this replaces each re-parsed the tree)."""
        t0 = time.perf_counter()
        report = run_all(Project())
        wall = time.perf_counter() - t0
        assert set(report["passes"]) == ALL_RULES
        assert report["files_scanned"] > 100
        new = "\n".join(str(f) for f in report["new"])
        assert report["new"] == [], f"new findings:\n{new}"
        assert wall < 10.0, f"suite took {wall:.1f}s (budget 10s)"

    def test_cli_module_entrypoint(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analysis"], cwd=REPO,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "11 passes" in proc.stdout

    def test_lock_order_graph_is_exposed(self):
        # bench/debug introspection surface: the cross-module edge list
        # derives without error (cycles over it fail the suite itself)
        edges = lock_discipline.lock_order_edges(Project())
        assert isinstance(edges, list)


# ========================================= regressions for fixed races

class TestRaceFixRegressions:
    """Each race the annotation sweep surfaced got a code fix; these
    prove the fixed paths actually serialize on their lock."""

    def test_flight_note_step_pairs_under_lock(self):
        from paddle_tpu.observability.flight import FlightRecorder

        rec = FlightRecorder(registry=None, tracer=None, emit_spans=False)
        rec.note_step(3, epoch=1)
        assert rec.progress() == (3, 1)
        _assert_needs_lock(rec._lock, lambda: rec.note_step(4, epoch=2),
                           "FlightRecorder.note_step")
        _assert_needs_lock(rec._lock, rec.progress,
                           "FlightRecorder.progress")
        assert rec.progress() == (4, 2)

    def test_tracer_summary_reads_under_lock(self):
        from paddle_tpu.observability.tracing import Tracer

        tr = Tracer()
        with tr.start_trace("step"):
            pass
        _assert_needs_lock(tr._lock, tr.summary, "Tracer.summary")
        s = tr.summary()
        assert s["completed"] == 1 and s["buffered"] == 1

    def test_aggregator_fleet_state_under_lock(self):
        from paddle_tpu.observability.aggregate import ClusterAggregator
        from paddle_tpu.observability.metrics import MetricsRegistry

        class _NoStore:
            pass

        agg = ClusterAggregator(_NoStore(), world_size=2,
                                registry=MetricsRegistry())
        _assert_needs_lock(
            agg._lock, lambda: agg.merged_snapshot(collect=False),
            "ClusterAggregator.merged_snapshot")
        _assert_needs_lock(
            agg._lock, lambda: agg.expose_prometheus(collect=False),
            "ClusterAggregator.expose_prometheus")

    def test_aggregator_no_torn_fleet_view(self):
        """Stress the exporter-vs-collect race the lock now prevents: a
        reader must never see a fresh rank set paired with the previous
        collect's stale/missing lists (the set sizes always partition
        world_size)."""
        from paddle_tpu.observability.aggregate import ClusterAggregator
        from paddle_tpu.observability.metrics import MetricsRegistry

        world = 4

        class _FlipStore:
            """All ranks fresh on odd collects, all missing on even."""

            def __init__(self):
                self.n = 0

            def mget(self, keys, value_size_hint=0):
                self.n += 1
                if self.n % 2:
                    now = time.time()
                    return [json.dumps({"rank": i, "time": now,
                                        "metrics": {}})
                            for i in range(len(keys))]
                return [None] * len(keys)

        agg = ClusterAggregator(_FlipStore(), world_size=world,
                                registry=MetricsRegistry())
        agg.collect()
        stop = threading.Event()
        torn = []

        def reader():
            while not stop.is_set():
                snap = agg.merged_snapshot(collect=False)
                total = (len(snap["ranks"]) + len(snap["stale_ranks"])
                         + len(snap["missing_ranks"]))
                if total != world:
                    torn.append(snap)
                    return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        deadline = time.time() + 0.5
        while time.time() < deadline:
            agg.collect()
        stop.set()
        t.join(timeout=5.0)
        assert torn == [], f"torn fleet view observed: {torn[:1]}"

    def test_flags_reads_serialize_with_writes(self):
        from paddle_tpu.core import flags as flags_mod

        flags_mod.define_flag("_lint_test_flag", 1)
        _assert_needs_lock(flags_mod._lock,
                           lambda: flags_mod.get_flags("_lint_test_flag"),
                           "flags.get_flags")
        assert flags_mod.get_flags("_lint_test_flag") == \
            {"_lint_test_flag": 1}

    def test_resource_sampler_last_sample_under_lock(self):
        from paddle_tpu.observability.exporter import ResourceSampler
        from paddle_tpu.observability.metrics import MetricsRegistry

        s = ResourceSampler(registry=MetricsRegistry())
        s.sample_once()
        _assert_needs_lock(s._lock, lambda: s.last_sample,
                           "ResourceSampler.last_sample")
        assert s.last_sample["rss_bytes"] is not None or True

    def test_checkpoint_manager_error_handoff_locked(self, tmp_path):
        from paddle_tpu.resilience.checkpoint_manager import \
            CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        _assert_needs_lock(mgr._lock, mgr.wait, "CheckpointManager.wait")

    def test_checkpoint_manager_async_error_still_surfaces(self, tmp_path,
                                                           monkeypatch):
        from paddle_tpu.resilience import checkpoint_manager as cm

        mgr = cm.CheckpointManager(str(tmp_path / "ckpt"),
                                   async_save=True)

        def boom(tree, step, extra, verify=False):
            raise RuntimeError("disk gone")

        monkeypatch.setattr(mgr, "_write_and_commit", boom)
        mgr.save({"w": [1.0]}, step=1)
        with pytest.raises(RuntimeError, match="disk gone"):
            mgr.wait()
        # the error slot drains: a later wait() must not re-raise
        mgr.wait()

    def test_detector_would_catch_the_aggregate_regression(self, tmp_path):
        """The exact shape of the fixed aggregate.py bug, as a fixture:
        rendering methods reading collect()-written state without the
        lock must fire the detector (this is the guard against the fix
        regressing)."""
        src = """\
        import threading


        class Agg:
            def __init__(self):
                self._lock = threading.Lock()
                self._last = {}         # guarded-by: self._lock
                self.stale = []         # guarded-by: self._lock

            def collect(self, fresh, stale):
                with self._lock:
                    self._last = fresh
                    self.stale = stale

            def render(self):
                return dict(self._last), list(self.stale)
        """
        p = _project(tmp_path, {"agg.py": src})
        flagged = _findings("lock-discipline", p)
        assert len(flagged) == 2
        assert all("render" in f.message for f in flagged)


# ==================================================== collective-discipline

_COLLECTIVE_HEADER = """\
from ..distributed.collective import all_reduce, all_gather
"""


class TestCollectiveDiscipline:
    """ISSUE 13 fixture matrix: the static complement of the PR 8
    hang watchdog — rank-divergent collectives, order divergence and
    unbounded distributed waits caught before any rank wedges."""

    def _findings(self, tmp_path, src, extra=None):
        files = {"m.py": _COLLECTIVE_HEADER + textwrap.dedent(src)}
        files.update(extra or {})
        p = _project(tmp_path, files)
        return _findings("collective-discipline", p)

    def test_rank_conditional_hang(self, tmp_path):
        """THE acceptance fixture: the hang the runtime watchdog only
        catches after the fleet is wedged, flagged statically."""
        flagged = self._findings(tmp_path, """\
            def step(x, rank):
                if rank == 0:
                    x = all_reduce(x)
                return x
            """)
        assert len(flagged) == 1
        assert "rank-conditional hang" in flagged[0].message
        assert "all_reduce" in flagged[0].message

    def test_guard_return_counts_as_branch(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            def step(x, rank):
                if rank != 0:
                    return x
                return all_reduce(x)
            """)
        assert len(flagged) == 1
        assert "rank-conditional hang" in flagged[0].message
        assert "guard return" in flagged[0].message

    def test_order_divergence_between_branches(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            def step(x, rank):
                if rank == 0:
                    x = all_reduce(x)
                    x = all_gather(x)
                else:
                    x = all_gather(x)
                    x = all_reduce(x)
                return x
            """)
        assert len(flagged) == 1
        assert "order divergence" in flagged[0].message
        assert "all_reduce -> all_gather" in flagged[0].message

    def test_identical_sequences_are_clean(self, tmp_path):
        assert self._findings(tmp_path, """\
            def step(x, rank):
                if rank == 0:
                    x = all_reduce(x)
                    log = True
                else:
                    x = all_reduce(x)
                return x
            """) == []

    def test_uniform_collective_outside_branch_is_clean(self, tmp_path):
        assert self._findings(tmp_path, """\
            def step(x, rank):
                x = all_reduce(x)
                if rank == 0:
                    print("leader")
                return x
            """) == []

    def test_predicate_resolved_one_call_deep(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            def should_lead():
                return get_rank() == 0


            def step(x):
                if should_lead():
                    x = all_reduce(x)
                return x
            """)
        assert len(flagged) == 1
        assert "rank-conditional hang" in flagged[0].message

    def test_predicate_resolved_across_modules(self, tmp_path):
        files = {
            "util.py": """\
            def leader():
                return get_rank() == 0
            """,
            "main.py": _COLLECTIVE_HEADER + textwrap.dedent("""\
                from pkg.util import leader


                def step(x):
                    if leader():
                        x = all_reduce(x)
                    return x
                """),
        }
        p = _project(tmp_path, files)
        flagged = _findings("collective-discipline", p)
        assert len(flagged) == 1
        assert flagged[0].file == "pkg/main.py"

    def test_rank_tainted_local(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            def step(x, rank):
                primary = rank == 0
                if primary:
                    x = all_reduce(x)
                return x
            """)
        assert len(flagged) == 1

    def test_collective_collected_one_call_deep(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            def _sync(x):
                return all_reduce(x)


            def step(x, rank):
                if rank == 0:
                    x = _sync(x)
                return x
            """)
        assert len(flagged) == 1
        assert "rank-conditional hang" in flagged[0].message

    def test_rank_ok_sanctions_the_protocol(self, tmp_path):
        assert self._findings(tmp_path, """\
            def step(x, rank):
                if rank == 0:   # rank-ok: leader-only warmup collective
                    x = all_reduce(x)
                return x
            """) == []

    def test_lint_ok_also_suppresses(self, tmp_path):
        assert self._findings(tmp_path, """\
            def step(x, rank):
                if rank == 0:
                    # lint-ok: collective-discipline vetted protocol
                    x = all_reduce(x)
                return x
            """) == []

    def test_handshake_pairing_is_sanctioned(self, tmp_path):
        """The begin/ack/commit shape: one side publishes what the
        other blocks on — not a hang."""
        assert self._findings(tmp_path, """\
            def open_generation(store, rank):
                if rank == 0:
                    store.set("gen", "1")
                else:
                    store.get("gen", timeout=5.0)
            """) == []

    def test_one_sided_wait_without_publish_flagged(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            def step(store, rank):
                if rank == 0:
                    count = 1
                else:
                    store.get("gen", timeout=5.0)
            """)
        assert len(flagged) == 1
        assert "one-sided blocking wait" in flagged[0].message

    def test_timeout_less_wait_flagged(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            def fetch(store, key):
                return store.get(key)
            """)
        assert len(flagged) == 1
        assert "unbounded blocking wait" in flagged[0].message

    def test_timeout_kwarg_is_clean(self, tmp_path):
        assert self._findings(tmp_path, """\
            def fetch(store, key):
                return store.get(key, timeout=5.0)
            """) == []

    def test_deadline_in_scope_is_clean(self, tmp_path):
        assert self._findings(tmp_path, """\
            def fetch(store, key, deadline):
                return store.get(key, timeout=deadline.remaining())
            """) == []

    def test_forwarded_none_default_flagged(self, tmp_path):
        """The TCPStore.wait shape this PR fixed: timeout= forwards a
        parameter defaulting to None — no total bound on the default
        path."""
        flagged = self._findings(tmp_path, """\
            def wait_all(store, keys, timeout=None):
                for k in keys:
                    store.get(k, timeout=timeout)
            """)
        assert len(flagged) == 1
        assert "defaults to None" in flagged[0].message

    def test_nonblocking_get_is_clean(self, tmp_path):
        assert self._findings(tmp_path, """\
            def probe(store, key):
                return store.get(key, blocking=False)
            """) == []

    def test_store_barrier_without_timeout_flagged(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            def sync(store):
                store.barrier()
            """)
        assert len(flagged) == 1
        assert "barrier" in flagged[0].message

    def test_repo_collective_sites_nonempty(self):
        """The pass must actually see the repo's collective plane — an
        empty site list would make the clean tier-1 run vacuous."""
        sites = collective_discipline.collective_sites(Project())
        assert len(sites) >= 10
        files = {s[0] for s in sites}
        assert "paddle_tpu/distributed/collective.py" in files
        assert "paddle_tpu/distributed/checkpoint.py" in files
        ops = {s[3] for s in sites}
        assert "barrier.ack" in ops and "barrier.commit" in ops

    def test_checkpoint_py_clean_on_merit(self):
        """The asymmetric rank-0 commit protocol passes with NO
        baseline: store ops are handshake-class and every uniform
        begin/ack/commit is issued on all ranks."""
        p = Project()
        flagged = [f for f in apply_suppressions(
            p, REGISTRY["collective-discipline"](p))
            if f.file.endswith("distributed/checkpoint.py")]
        assert flagged == []
        assert load_baseline("collective-discipline") == set()


# =========================================================== sharding-spec

_MESH_FIXTURE = {"mesh.py": 'AXIS_ORDER = ("dp", "mp")\n'}


class TestShardingSpec:
    def _findings(self, tmp_path, src, mesh=True):
        files = {"specs.py": textwrap.dedent(src)}
        if mesh:
            files.update(_MESH_FIXTURE)
        p = _project(tmp_path, files)
        return _findings("sharding-spec", p)

    def test_unknown_axis_flagged(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            from jax.sharding import PartitionSpec as P

            SPEC = P("bogus", None)
            """)
        assert len(flagged) == 1
        assert "unknown mesh axis 'bogus'" in flagged[0].message

    def test_axes_from_mesh_constructions_count(self, tmp_path):
        """An axis declared by any Mesh(...) in the package (the
        hybrid engine's 'sep'/'ep') is known, not just AXIS_ORDER."""
        assert self._findings(tmp_path, """\
            from jax.sharding import Mesh, PartitionSpec as P

            MESH = Mesh(devs, ("sep",))
            SPEC = P("sep")
            """) == []

    def test_duplicate_axis_flagged(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            from jax.sharding import PartitionSpec as P

            SPEC = P("mp", "mp")
            """)
        assert len(flagged) == 1
        assert "appears twice" in flagged[0].message

    def test_duplicate_inside_tuple_entry(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            from jax.sharding import PartitionSpec as P

            SPEC = P(("dp", "mp"), "mp")
            """)
        assert len(flagged) == 1
        assert "appears twice" in flagged[0].message

    def test_no_mesh_declared_skips_axis_check(self, tmp_path):
        # nothing to validate against -> silent, not noisy
        assert self._findings(tmp_path, """\
            from jax.sharding import PartitionSpec as P

            SPEC = P("anything")
            """, mesh=False) == []

    def test_donate_arity_mismatch_flagged(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            import jax


            def build(fn, sh):
                return jax.jit(fn, in_shardings=(sh, sh),
                               donate_argnums=(0, 2))
            """)
        assert len(flagged) == 1
        assert "donate/sharding arity mismatch" in flagged[0].message

    def test_donate_arity_via_kwargs_dict(self, tmp_path):
        """The hapi idiom: jit_kw built up then **splatted."""
        flagged = self._findings(tmp_path, """\
            import jax


            def build(fn, sh):
                jit_kw = dict(in_shardings=(sh, sh))
                jit_kw["donate_argnums"] = (0, 3)
                return jax.jit(fn, **jit_kw)
            """)
        assert len(flagged) == 1
        assert "donate/sharding arity mismatch" in flagged[0].message

    def test_consistent_donate_arity_clean(self, tmp_path):
        assert self._findings(tmp_path, """\
            import jax


            def build(fn, sh):
                jit_kw = dict(in_shardings=(sh, sh) + (sh,) * 4)
                jit_kw.update(donate_argnums=(0, 2))
                return jax.jit(fn, **jit_kw)
            """) == []

    def test_unresolvable_operands_skipped(self, tmp_path):
        # variables the pass can't resolve must not guess
        assert self._findings(tmp_path, """\
            import jax


            def build(fn, shardings, donate):
                return jax.jit(fn, in_shardings=shardings,
                               donate_argnums=donate)
            """) == []

    def test_dead_rule_shadowed_by_earlier(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            from jax.sharding import PartitionSpec as P

            RULES = (
                (r"_w$", P(None, "mp")),
                (r"qkv_w$", P("mp", None)),
            )


            def use(x):
                return RULES
            """)
        assert len(flagged) == 1
        assert "dead rule" in flagged[0].message
        assert "qkv_w$" in flagged[0].message

    def test_anchored_rules_not_false_flagged(self, tmp_path):
        """The GPT table shape: '(^|[/_])wte$'-style anchored rules do
        not shadow each other."""
        assert self._findings(tmp_path, """\
            from jax.sharding import PartitionSpec as P

            RULES = (
                (r"(^|[/_])wte$", P("mp", None)),
                (r"qkv_w$", P(None, "mp")),
                (r"(ln\\d?|lnf)_[gb]$", P()),
            )


            def use(x):
                return RULES
            """) == []

    def test_unreferenced_table_flagged(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            from jax.sharding import PartitionSpec as P

            ORPHAN = (
                (r"x$", P("dp")),
            )
            """)
        assert len(flagged) == 1
        assert "referenced nowhere" in flagged[0].message

    def test_bad_regex_flagged(self, tmp_path):
        flagged = self._findings(tmp_path, """\
            from jax.sharding import PartitionSpec as P

            RULES = (
                (r"qkv_w[", P("mp")),
            )


            def use(x):
                return RULES
            """)
        assert len(flagged) == 1
        assert "does not compile" in flagged[0].message

    def test_suppression_applies(self, tmp_path):
        assert self._findings(tmp_path, """\
            from jax.sharding import PartitionSpec as P

            # lint-ok: sharding-spec future axis, mesh lands next PR
            SPEC = P("bogus")
            """) == []

    def test_repo_axis_universe(self):
        axes = sharding_spec.declared_axes(Project())
        for ax in ("dp", "mp", "pp", "sharding"):
            assert ax in axes, axes


# ======================================================== changed-only CLI

class TestChangedOnly:
    def test_changed_files_lists_dirty_and_untracked(self, tmp_path):
        import subprocess as sp

        repo = tmp_path / "r"
        repo.mkdir()
        env = dict(os.environ,
                   GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

        def git(*args):
            sp.run(["git", *args], cwd=str(repo), check=True, env=env,
                   capture_output=True)

        git("init", "-q")
        (repo / "a.py").write_text("x = 1\n")
        (repo / "b.py").write_text("y = 1\n")
        git("add", "-A")
        git("commit", "-qm", "seed")
        (repo / "a.py").write_text("x = 2\n")          # modified
        (repo / "new.py").write_text("z = 1\n")        # untracked
        changed = changed_files(repo_root=str(repo))
        assert changed == {"a.py", "new.py"}

    def test_scope_filters_findings_not_analysis(self, tmp_path):
        bad = """\
        def f():
            try:
                pass
            except Exception:
                pass
        """
        p = _project(tmp_path, {"one.py": bad, "two.py": bad})
        full, _, _ = run_pass(REGISTRY["excepts"], p,
                              baseline_dir=str(tmp_path / "bl"))
        assert {f.file for f in full} == {"pkg/one.py", "pkg/two.py"}
        scoped = Project(package_root=str(tmp_path / "pkg"),
                         tests_root=str(tmp_path / "tests"),
                         scope={"pkg/one.py"})
        got, _, _ = run_pass(REGISTRY["excepts"], scoped,
                             baseline_dir=str(tmp_path / "bl"))
        assert {f.file for f in got} == {"pkg/one.py"}
        assert [m.rel for m in scoped.scoped_modules()] == ["pkg/one.py"]
        # the full module universe stays loaded for cross-file passes
        assert {m.rel for m in scoped.modules()} == \
            {"pkg/one.py", "pkg/two.py"}

    def test_cli_changed_only_smoke(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analysis", "--changed-only"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        out = proc.stdout + proc.stderr
        # clean tree -> "no changed"; dirty dev tree -> scoped run over
        # files this PR keeps clean; either way no crash
        assert proc.returncode in (0, 1), out
        assert "tools.analysis" in out
        assert "scoped to" in out or "no changed" in out


# ============================================ fleet-router lock regression

class TestRouterLockRegression:
    """ISSUE 13 guarded-by sweep: the telemetry scrape thread reads
    fleet state while the driver mutates it mid-step — the router now
    serializes both on one re-entrant lock (same shape as the PR 11
    aggregator fix)."""

    def _router(self):
        from paddle_tpu.serving.router import FleetRouter

        from paddle_tpu.serving.engine import RequestState

        class _Req:
            state = RequestState.REJECTED
            finish_reason = "stub"

        class _Eng:
            def health(self):
                return {"estimated_drain_s": 0.0, "queue_depth": 0,
                        "running": 0}

            def has_work(self):
                return False

            def evacuate(self):
                pass

            def add_request(self, prompt, sampling, trace_context=None):
                return _Req()

        return FleetRouter([_Eng()])

    def test_fleet_views_and_submit_under_lock(self):
        router = self._router()
        _assert_needs_lock(router._lock, router.fleet_health,
                           "FleetRouter.fleet_health")
        _assert_needs_lock(router._lock, router.fleet_status,
                           "FleetRouter.fleet_status")
        _assert_needs_lock(router._lock, router.has_work,
                           "FleetRouter.has_work")
        _assert_needs_lock(router._lock, lambda: router.submit([1, 2]),
                           "FleetRouter.submit")

    def test_step_holds_the_lock_through_admission(self):
        router = self._router()
        router.submit([1, 2, 3])
        _assert_needs_lock(router._lock, router.step,
                           "FleetRouter.step (admission path)")
