"""TrainingSupervisor tests: autonomous relaunch on elastic-exit /
crash / lost node, fault-matrix sites for kill-during-relaunch and
store-outage-during-rendezvous, and the end-to-end acceptance run — a
trainer killed mid-epoch (twice: once on the first run, once during
the recovery run) relaunches with zero operator action and reproduces
the uninterrupted loss curve bitwise.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.elastic import (ELASTIC_EXIT_CODE,
                                                  ElasticManager)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.observability import default_registry
from paddle_tpu.resilience import (FaultSpec, TrainingSupervisor,
                                   injected_faults)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _restarts(reason):
    fam = default_registry().get("supervisor_restarts_total")
    return fam.labels(reason=reason).value if fam else 0


def _script(tmp_path, body):
    p = tmp_path / "child.py"
    p.write_text("import os, sys\n"
                 "attempt = int(os.environ.get("
                 "'PADDLE_RESTART_ATTEMPT', '0'))\n" + body)
    return [sys.executable, str(p)]


def _mgr(store, host, np_=1, **kw):
    kw.setdefault("heartbeat_interval", 0.1)
    kw.setdefault("node_timeout", 0.4)
    return ElasticManager(store, job_id="sup", np=np_, host=host, **kw)


class TestSupervisorRelaunch:
    def test_clean_exit_passthrough(self, tmp_path):
        sup = TrainingSupervisor(_script(tmp_path, "sys.exit(0)\n"),
                                 max_restarts=3, backoff_base=0.01)
        assert sup.run() == 0
        assert sup.restarts == []

    def test_elastic_exit_relaunches_and_resumes(self, tmp_path):
        """ELASTIC_EXIT_CODE is a relaunch *request*: attempt 0 asks,
        attempt 1 completes.  The resume env contract reaches every
        attempt identically (first launch == Nth relaunch)."""
        before = _restarts("elastic_exit")
        body = (
            "assert os.environ['PADDLE_ELASTIC_RESUME_DIR'] == "
            f"{str(tmp_path / 'ck')!r}\n"
            "with open(os.path.join("
            f"{str(tmp_path)!r}, 'runs.log'), 'a') as f:\n"
            "    f.write(f'attempt={attempt}\\n')\n"
            f"sys.exit({ELASTIC_EXIT_CODE} if attempt == 0 else 0)\n")
        sup = TrainingSupervisor(_script(tmp_path, body),
                                 checkpoint_dir=str(tmp_path / "ck"),
                                 max_restarts=2, backoff_base=0.01,
                                 backoff_cap=0.02)
        assert sup.run() == 0
        assert sup.restarts == [("elastic_exit", 1)]
        assert _restarts("elastic_exit") == before + 1
        runs = (tmp_path / "runs.log").read_text().splitlines()
        assert runs == ["attempt=0", "attempt=1"]

    def test_restart_budget_exhaustion_propagates_code(self, tmp_path):
        sup = TrainingSupervisor(_script(tmp_path, "sys.exit(3)\n"),
                                 max_restarts=2, backoff_base=0.01,
                                 backoff_cap=0.02)
        assert sup.run() == 3
        assert [r for r, _ in sup.restarts] == ["crash", "crash"]

    @pytest.mark.faultinject
    def test_kill_during_relaunch_survived(self, tmp_path):
        """Fault-matrix site supervisor.spawn: the RELAUNCH itself dies
        (io_error spawning attempt 1) on top of the original crash —
        the supervisor burns another unit of restart budget and still
        completes."""
        before = _restarts("spawn_failed")
        body = ("with open(os.path.join("
                f"{str(tmp_path)!r}, 'runs.log'), 'a') as f:\n"
                "    f.write(f'attempt={attempt}\\n')\n"
                "sys.exit(7 if attempt == 0 else 0)\n")
        sup = TrainingSupervisor(_script(tmp_path, body),
                                 max_restarts=3, backoff_base=0.01,
                                 backoff_cap=0.02)
        with injected_faults(FaultSpec("supervisor.spawn", "io_error",
                                       occurrence=2)):
            assert sup.run() == 0
        assert [r for r, _ in sup.restarts] == ["crash", "spawn_failed"]
        assert _restarts("spawn_failed") == before + 1
        runs = (tmp_path / "runs.log").read_text().splitlines()
        assert runs == ["attempt=0", "attempt=2"]


class TestSupervisorElastic:
    @pytest.mark.faultinject
    def test_store_outage_during_rendezvous_retried(self, tmp_path):
        """Fault-matrix site supervisor.rendezvous: a transient store
        outage while waiting for membership is retried with backoff —
        it must not read as a dead fleet or crash the supervisor."""
        store = TCPStore(is_master=True, world_size=1)
        sup = TrainingSupervisor(
            _script(tmp_path, "sys.exit(0)\n"),
            elastic=_mgr(store, "me"), hosts=["me"],
            max_restarts=1, backoff_base=0.01, backoff_cap=0.02,
            rendezvous_timeout=20.0)
        with injected_faults(FaultSpec("supervisor.rendezvous",
                                       "io_error", occurrence=1)):
            assert sup.run() == 0

    def test_lost_node_terminates_and_relaunches(self, tmp_path):
        """A dead peer mid-run: the supervisor kills the local trainer,
        re-rendezvouses (waiting for the replacement), and relaunches."""
        store = TCPStore(is_master=True, world_size=2)
        peer = _mgr(store, "peer", np_=2)
        peer.register()
        before = _restarts("lost_node")
        # attempt 0 hangs (a trainer wedged on a dead peer's collective);
        # attempt 1 completes
        body = ("import time\n"
                "time.sleep(60 if attempt == 0 else 0)\n"
                "sys.exit(0)\n")
        sup = TrainingSupervisor(
            _script(tmp_path, body),
            elastic=_mgr(store, "me", np_=2), hosts=["me", "peer"],
            max_restarts=1, backoff_base=0.01, backoff_cap=0.02,
            membership_interval=0.1, rendezvous_timeout=30.0,
            term_grace_s=5.0)
        holder = {}

        def chaos():
            time.sleep(1.8)            # past the first rendezvous
            peer.deregister()          # peer dies mid-run
            time.sleep(1.0)
            holder["peer2"] = _mgr(store, "peer", np_=2).register()

        t = threading.Thread(target=chaos, daemon=True)
        t.start()
        try:
            assert sup.run() == 0
        finally:
            t.join()
            holder["peer2"].deregister()
        assert [r for r, _ in sup.restarts] == ["lost_node"]
        assert _restarts("lost_node") == before + 1


# ------------------------------------------------- end-to-end acceptance

# A real hapi trainer: 2 epochs x 4 steps on the PR-3 toy problem, a
# CheckpointCallback every step, fit(resume_from=<supervisor contract>).
# Attempts 0 and 1 install a kill fault (the second one DURING the
# recovery run — kill-during-relaunch); attempt 2 runs clean.  Each
# completed step appends "global_step repr(loss)" to losses.log.
E2E_TRAINER = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Callback, CheckpointCallback, Model
from paddle_tpu.io import Dataset
from paddle_tpu.resilience import FaultInjector, FaultSpec, install

attempt = int(os.environ.get("PADDLE_RESTART_ATTEMPT", "0"))
resume = os.environ.get("PADDLE_ELASTIC_RESUME_DIR")
KILLS = {0: 6, 1: 1}    # attempt -> hapi.train_step kill occurrence
if attempt in KILLS:
    install(FaultInjector([FaultSpec("hapi.train_step", "kill",
                                     occurrence=KILLS[attempt])]))

class Toy(Dataset):
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.y = rng.randint(0, 2, (n,)).astype(np.int64)
        self.x = (rng.randn(n, 8) * 0.3 +
                  self.y[:, None].astype(np.float32) * 2.0
                  ).astype(np.float32)
    def __len__(self):
        return len(self.x)
    def __getitem__(self, i):
        return self.x[i], self.y[i]

class Rec(Callback):
    def __init__(self, path):
        super().__init__()
        self.path = path
        self.gstep = 0
    def on_train_begin(self, logs=None):
        info = getattr(self.model, "_resume_info", None) or {}
        self.gstep = int(info.get("global_step", 0))
    def on_train_batch_end(self, step, logs=None):
        self.gstep += 1
        with open(self.path, "a") as f:
            f.write(f"{self.gstep} {logs['loss']!r}\\n")
            f.flush()

paddle.seed(3)
net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
model = Model(net)
opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                parameters=model.parameters())
model.prepare(opt, nn.CrossEntropyLoss())
cbs = [Rec(os.environ["E2E_LOSS_LOG"])]
if resume:
    cbs.append(CheckpointCallback(resume, every_n_steps=1))
model.fit(Toy(), batch_size=16, epochs=2, shuffle=False, verbose=0,
          callbacks=cbs, resume_from=resume)
"""


def _clean_env(**extra):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "XLA_", "JAX_"))}
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


def _read_losses(path):
    out = {}
    with open(path) as f:
        for line in f:
            gstep, loss = line.split(" ", 1)
            out[int(gstep)] = float(loss)
    return out


@pytest.mark.faultinject
class TestSupervisorEndToEnd:
    def test_killed_trainer_resumes_bitwise(self, tmp_path):
        """Kill the trainer mid-epoch, then kill the recovery run too:
        the supervisor relaunches both times with zero operator action
        and the assembled loss curve equals an uninterrupted run's,
        bitwise."""
        script = tmp_path / "trainer.py"
        script.write_text(E2E_TRAINER)

        # uninterrupted reference in an identical subprocess environment
        # (attempt 99 installs no faults; fresh checkpoint dir)
        ref_log = tmp_path / "ref.log"
        proc = subprocess.run(
            [sys.executable, str(script)], cwd=REPO, timeout=300,
            env=_clean_env(E2E_LOSS_LOG=str(ref_log),
                           PADDLE_RESTART_ATTEMPT="99",
                           PADDLE_ELASTIC_RESUME_DIR=str(
                               tmp_path / "ck_ref")),
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr[-2000:]
        ref = _read_losses(ref_log)
        assert sorted(ref) == list(range(1, 9))

        # supervised run: attempt 0 killed at global step 6, attempt 1
        # (the recovery run) killed at its first step, attempt 2 clean
        loss_log = tmp_path / "sup.log"
        ckdir = tmp_path / "ck"
        sup = TrainingSupervisor(
            [sys.executable, str(script)], checkpoint_dir=str(ckdir),
            max_restarts=3, backoff_base=0.01, backoff_cap=0.05,
            env=_clean_env(E2E_LOSS_LOG=str(loss_log)),
            log_path=str(tmp_path / "sup_child.log"))
        assert sup.run() == 0
        assert [r for r, _ in sup.restarts] == ["crash", "crash"]

        got = _read_losses(loss_log)
        assert sorted(got) == list(range(1, 9))
        np.testing.assert_array_equal(
            np.asarray([got[s] for s in range(1, 9)]),
            np.asarray([ref[s] for s in range(1, 9)]))
        # the supervisor saw the resume point advance across attempts
        assert sup._resume_step() == 8
