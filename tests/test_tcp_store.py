"""Native TCPStore tests (reference: tcp_store.cc semantics; the C++
server/client compile on first use with the image's g++)."""
import multiprocessing as mp
import time

import pytest

from paddle_tpu.distributed.store import TCPStore


def _wait_worker(port, q):
    st = TCPStore(port=port, is_master=False, world_size=2)
    q.put(st.get("late-key", blocking=True))  # blocks until set


def _barrier_worker(port, q):
    st = TCPStore(port=port, is_master=False, world_size=3)
    st.barrier("b1", timeout=30)
    q.put(time.time())


class TestTCPStore:
    def test_set_get(self):
        master = TCPStore(is_master=True, world_size=1)
        master.set("k", b"hello")
        assert master.get("k") == b"hello"
        master.set("k", "text-value")
        assert master.get("k") == b"text-value"

    def test_get_nonblocking_missing(self):
        master = TCPStore(is_master=True, world_size=1)
        with pytest.raises(KeyError):
            master.get("nope", blocking=False)

    def test_add_counter(self):
        master = TCPStore(is_master=True, world_size=1)
        assert master.add("c", 1) == 1
        assert master.add("c", 5) == 6
        assert master.add("c", -2) == 4

    def test_second_client_sees_master_data(self):
        master = TCPStore(is_master=True, world_size=2)
        client = TCPStore(port=master.port, is_master=False, world_size=2)
        master.set("from_master", b"x")
        assert client.get("from_master") == b"x"
        client.set("from_client", b"y")
        assert master.get("from_master") == b"x"
        assert master.get("from_client") == b"y"

    def test_blocking_wait_across_processes(self):
        master = TCPStore(is_master=True, world_size=2)
        port = master.port

        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_wait_worker, args=(port, q))
        p.start()
        time.sleep(0.5)           # worker is (very likely) blocked in wait
        master.set("late-key", b"released")
        assert q.get(timeout=30) == b"released"
        p.join(timeout=10)
        assert p.exitcode == 0

    def test_barrier_across_processes(self):
        master = TCPStore(is_master=True, world_size=3)
        port = master.port

        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_barrier_worker, args=(port, q))
                 for _ in range(2)]
        for p in procs:
            p.start()
        time.sleep(0.5)
        t_release = time.time()
        master.barrier("b1", timeout=30)   # third participant releases all
        times = [q.get(timeout=30) for _ in procs]
        for p in procs:
            p.join(timeout=10)
        assert all(t >= t_release - 0.2 for t in times)

    def test_connect_timeout(self):
        with pytest.raises(TimeoutError):
            TCPStore(host="127.0.0.1", port=1, is_master=False,
                     world_size=1, timeout=0.5)

    def test_value_larger_than_client_buffer(self):
        # values over the 1 MiB first-try buffer must round-trip (the
        # server reports the exact length; one exact-size retry)
        store = TCPStore(is_master=True, world_size=1)
        big = bytes(range(256)) * (9 * 4096)   # 9 MiB
        store.set("big", big)
        assert store.get("big", blocking=False) == big

    def test_set_if_absent(self):
        store = TCPStore(is_master=True, world_size=1)
        assert store.set_if_absent("k", b"first")
        assert not store.set_if_absent("k", b"second")
        assert store.get("k", blocking=False) == b"first"
