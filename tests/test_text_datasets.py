"""Local-archive parsers for the Conll05/Movielens/WMT corpora
(VERDICT r4 item 8): synthetic archives built in the OFFICIAL layouts
(conll05st-release words/props gz-in-tar, ml-1m ::-separated zip,
wmt14 src.dict/trg.dict + mode/mode tar) drive the real parse paths."""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text import WMT14, WMT16, Conll05, Movielens


def _tar_add(tf, name, data: bytes):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


@pytest.fixture()
def conll_archive(tmp_path):
    words = "\n".join(["The", "cat", "sat", "", "Dogs", "bark", ""]) + "\n"
    # two sentences; first has one frame (predicate 'sat'), second one
    # frame (predicate 'bark'); column 0 = target verbs, column 1 = spans
    props = "\n".join([
        "-    (A0*",
        "-    *)",
        "sat  (V*)",
        "",
        "-     (A0*)",
        "bark  (V*)",
        "",
    ]) + "\n"
    path = tmp_path / "conll05st-tests.tar.gz"
    with tarfile.open(path, "w:gz") as tf:
        _tar_add(tf, Conll05.WORDS_MEMBER, gzip.compress(words.encode()))
        _tar_add(tf, Conll05.PROPS_MEMBER, gzip.compress(props.encode()))
    return str(path)


class TestConll05:
    def test_parses_frames_and_bio(self, conll_archive):
        ds = Conll05(conll_archive)
        assert len(ds) == 2
        words, pred, labels = ds[0]
        assert words == ["The", "cat", "sat"]
        assert pred == "sat"
        assert labels == ["B-A0", "I-A0", "B-V"]
        words, pred, labels = ds[1]
        assert words == ["Dogs", "bark"]
        assert pred == "bark"
        assert labels == ["B-A0", "B-V"]

    def test_dict_mode(self, conll_archive):
        wd = {"<unk>": 0, "The": 1, "cat": 2, "sat": 3}
        ld = {"B-A0": 0, "I-A0": 1, "B-V": 2, "O": 3}
        ds = Conll05(conll_archive, word_dict=wd, label_dict=ld)
        words, pred, labels = ds[0]
        np.testing.assert_array_equal(words, [1, 2, 3])
        np.testing.assert_array_equal(pred, [3])
        np.testing.assert_array_equal(labels, [0, 1, 2])

    def test_missing_file_is_loud(self):
        with pytest.raises(Exception, match="Conll05|egress|local"):
            Conll05(None)


@pytest.fixture()
def ml1m_archive(tmp_path):
    path = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("ml-1m/movies.dat",
                   "1::Toy Story (1995)::Animation|Comedy\n"
                   "2::Heat (1995)::Action|Crime\n")
        z.writestr("ml-1m/users.dat",
                   "1::M::25::12::90210\n2::F::35::7::10001\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::1::5::978300760\n1::2::3::978302109\n"
                   "2::1::4::978301968\n2::2::2::978300275\n")
    return str(path)


class TestMovielens:
    def test_feature_tuple(self, ml1m_archive):
        tr = Movielens(ml1m_archive, mode="train", test_ratio=0.0)
        assert len(tr) == 4
        uid, g, age, job, mid, cats, title, rating = tr[0]
        assert uid.shape == (1,) and mid.shape == (1,)
        assert rating.dtype == np.float32
        # ratings are rescaled to [-3, 5]: r*2-5
        all_ratings = sorted(float(s[7][0]) for s in
                             (tr[i] for i in range(4)))
        assert all_ratings == [-1.0, 1.0, 3.0, 5.0]
        # two categories per movie, title words dictionary-coded
        assert cats.shape[0] == 2
        assert title.shape[0] == 2          # "Toy Story" / "Heat"->1? no:
        # item 0 is the first kept line (user 1, movie 1: "Toy Story")

    def test_split_is_deterministic_and_disjoint(self, ml1m_archive):
        a = Movielens(ml1m_archive, mode="train", test_ratio=0.5)
        b = Movielens(ml1m_archive, mode="test", test_ratio=0.5)
        c = Movielens(ml1m_archive, mode="train", test_ratio=0.5)
        assert len(a) + len(b) == 4
        assert len(a) == len(c)
        for x, y in zip(a, c):
            for xa, ya in zip(x, y):
                np.testing.assert_array_equal(xa, ya)


@pytest.fixture()
def wmt_archive(tmp_path):
    path = tmp_path / "wmt14.tgz"
    src_dict = "<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = "<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    train = "hello world\tbonjour monde\nhello\tbonjour\n"
    test = "world\tmonde\n"
    with tarfile.open(path, "w:gz") as tf:
        _tar_add(tf, "wmt14/src.dict", src_dict.encode())
        _tar_add(tf, "wmt14/trg.dict", trg_dict.encode())
        _tar_add(tf, "wmt14/train/train", train.encode())
        _tar_add(tf, "wmt14/test/test", test.encode())
    return str(path)


class TestWMT:
    def test_train_ids(self, wmt_archive):
        ds = WMT14(wmt_archive, mode="train", dict_size=100)
        assert len(ds) == 2
        src, trg, trg_next = ds[0]
        # <s> hello world <e>
        np.testing.assert_array_equal(src, [0, 3, 4, 1])
        # <s> bonjour monde / bonjour monde <e>
        np.testing.assert_array_equal(trg, [0, 3, 4])
        np.testing.assert_array_equal(trg_next, [3, 4, 1])

    def test_test_mode_and_unk(self, wmt_archive):
        ds = WMT14(wmt_archive, mode="test", dict_size=100)
        assert len(ds) == 1
        src, trg, trg_next = ds[0]
        np.testing.assert_array_equal(src, [0, 4, 1])
        # dict_size cut: tiny dict maps known words, unknown -> UNK(2)
        small = WMT14(wmt_archive, mode="test", dict_size=3)
        s2, t2, _ = small[0]
        np.testing.assert_array_equal(s2, [0, 2, 1])   # 'world' -> UNK

    def test_wmt16_same_protocol(self, wmt_archive):
        ds = WMT16(wmt_archive, mode="train", dict_size=100)
        assert len(ds) == 2
