"""TimeSeriesStore unit matrix — hand-computed windowed queries on a
manual clock.

Every assertion is against a value computed by hand from the scripted
scrape history: rate/delta on a 10/s counter, least-squares slope on a
ramping gauge, histogram-bucket-delta quantiles with the interpolation
worked out on paper, and the counter-reset adjustment across a REAL
``ServingMetrics`` rebuild (``register(replace=True)`` mid-soak) —
windowed deltas must never read an engine restart as negative traffic.
The fixed budget (max_points ring, retention horizon, max_series cap)
and the nothing-starts-on-import discipline are pinned too.
"""
import threading
import time

import pytest

from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.timeseries import TimeSeriesStore
from paddle_tpu.serving.metrics import ServingMetrics


class _ManualClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _store(reg=None, **kw):
    clock = _ManualClock()
    reg = reg or MetricsRegistry()
    return reg, clock, TimeSeriesStore(registry=reg, clock=clock, **kw)


# ------------------------------------------------------ budget & hygiene


class TestBudgetAndHygiene:
    def test_nothing_starts_on_construction(self):
        before = {t.name for t in threading.enumerate()}
        _, _, store = _store()
        after = {t.name for t in threading.enumerate()}
        assert store._thread is None
        assert after == before

    def test_max_points_ring_bounded(self):
        reg, clock, store = _store(max_points=16)
        c = reg.counter("beats_total")
        for _ in range(50):
            clock.advance(1.0)
            c.inc()
            store.scrape_once()
        stats = store.stats()
        assert stats["points"] == 16
        assert stats["scrapes"] == 50
        # the newest 16 survive: the window still answers correctly
        assert store.delta("beats_total", window_s=10.0) == 10.0

    def test_retention_drops_old_points(self):
        reg, clock, store = _store(retention_s=5.0)
        g = reg.gauge("level")
        for i in range(20):
            clock.advance(1.0)
            g.set(float(i))
            store.scrape_once()
        (entry,) = store.stats()["names"]
        assert entry["first_t"] >= clock.t - 5.0
        assert entry["points"] <= 6

    def test_max_series_budget_is_fixed(self):
        reg, clock, store = _store(max_series=2)
        reg.counter("a_total"), reg.counter("b_total")
        clock.advance(1.0)
        store.scrape_once()
        reg.counter("c_total").inc()
        clock.advance(1.0)
        store.scrape_once()
        stats = store.stats()
        assert stats["series"] == 2
        assert stats["dropped_series"] >= 1
        assert store.delta("c_total", window_s=60.0) is None

    def test_optin_thread_scrapes_then_stops(self):
        reg = MetricsRegistry()
        reg.counter("beats_total").inc()
        store = TimeSeriesStore(registry=reg)     # wall perf_counter
        store.start(interval_s=0.005)
        deadline = time.monotonic() + 5.0
        while store.stats()["scrapes"] == 0 and \
                time.monotonic() < deadline:
            time.sleep(0.005)
        store.stop()
        assert store.stats()["scrapes"] > 0
        assert store._thread is None


# ----------------------------------------------------- windowed queries


class TestWindowedQueries:
    def test_rate_and_delta_hand_computed(self):
        reg, clock, store = _store()
        c = reg.counter("req_total")
        for _ in range(8):                        # t=1..8, +10 each
            clock.advance(1.0)
            c.inc(10)
            store.scrape_once()
        # window [4, 8]: points at t=4..8, cumulative 40..80
        assert store.delta("req_total", window_s=4.0) == 40.0
        assert store.rate("req_total", window_s=4.0) == pytest.approx(10.0)

    def test_delta_none_until_two_points_in_window(self):
        reg, clock, store = _store()
        c = reg.counter("req_total")
        assert store.delta("req_total", window_s=60.0) is None
        clock.advance(1.0)
        c.inc()
        store.scrape_once()
        assert store.delta("req_total", window_s=60.0) is None
        clock.advance(1.0)
        store.scrape_once()
        assert store.delta("req_total", window_s=60.0) == 0.0

    def test_family_delta_sums_children_and_labels_select(self):
        reg, clock, store = _store()
        c = reg.counter("dispatch_total", labelnames=("replica",))
        for _ in range(3):
            clock.advance(1.0)
            c.labels(replica="a").inc(2)
            c.labels(replica="b").inc(5)
            store.scrape_once()
        assert store.delta("dispatch_total", window_s=10.0) == 14.0
        assert store.delta("dispatch_total", labels={"replica": "b"},
                           window_s=10.0) == 10.0
        with pytest.raises(ValueError):
            store.delta("dispatch_total", labels={"wrong": "x"})

    def test_gauge_avg_and_slope_hand_computed(self):
        reg, clock, store = _store()
        g = reg.gauge("mem_bytes")
        for i in range(8):                        # t=1..8, 100 B/s ramp
            clock.advance(1.0)
            g.set(500.0 + 100.0 * i)
            store.scrape_once()
        # window [4, 8] -> samples 800,900,1000,1100,1200: mean 1000
        assert store.avg("mem_bytes", window_s=4.0) == pytest.approx(1000.0)
        assert store.slope("mem_bytes", window_s=8.0) == pytest.approx(100.0)

    def test_avg_ambiguous_across_family_raises(self):
        reg, clock, store = _store()
        g = reg.gauge("depth", labelnames=("q",))
        clock.advance(1.0)
        g.labels(q="a").set(1.0)
        g.labels(q="b").set(2.0)
        store.scrape_once()
        with pytest.raises(ValueError):
            store.avg("depth")
        assert store.avg("depth", labels={"q": "b"}) == 2.0

    def test_slope_none_without_two_distinct_times(self):
        reg, clock, store = _store()
        g = reg.gauge("level")
        clock.advance(1.0)
        g.set(5.0)
        store.scrape_once()
        assert store.slope("level", window_s=60.0) is None

    def test_quantile_hand_computed_interpolation(self):
        reg, clock, store = _store()
        # buckets: upper bounds 1, 2, 4, 8
        h = reg.histogram("lat_seconds", start=1.0, factor=2.0, count=4)
        clock.advance(1.0)
        store.scrape_once()                       # baseline point
        for v in (1.5, 1.5, 3.0, 7.0):
            h.observe(v)
        clock.advance(1.0)
        store.scrape_once()
        # bucket-count deltas: ub2 -> 2 obs, ub4 -> 1, ub8 -> 1 (total 4)
        # p50: rank 2 crosses ub2 -> 1 + (2-1) * 2/2 = 2.0
        assert store.quantile("lat_seconds", 50, window_s=5.0) == \
            pytest.approx(2.0)
        # p99: rank 3.96 crosses ub8 -> 4 + (8-4) * 0.96/1 = 7.84
        assert store.quantile("lat_seconds", 99, window_s=5.0) == \
            pytest.approx(7.84)
        # windowed != lifetime: observations OUTSIDE the window vanish
        clock.advance(100.0)
        store.scrape_once()
        assert store.quantile("lat_seconds", 99, window_s=5.0) is None

    def test_good_below_snaps_threshold_down(self):
        reg, clock, store = _store()
        h = reg.histogram("lat_seconds", start=1.0, factor=2.0, count=4)
        clock.advance(1.0)
        store.scrape_once()
        for v in (1.5, 1.5, 3.0, 7.0):
            h.observe(v)
        clock.advance(1.0)
        store.scrape_once()
        assert store.good_below("lat_seconds", 2.0, window_s=5.0) == \
            (2.0, 4.0)
        # threshold between bounds is conservative: 3.9 still only
        # counts buckets with ub <= 3.9 (ub 4 reads as bad)
        assert store.good_below("lat_seconds", 3.9, window_s=5.0) == \
            (2.0, 4.0)
        assert store.good_below("lat_seconds", 4.0, window_s=5.0) == \
            (3.0, 4.0)

    def test_query_payload_shapes(self):
        reg, clock, store = _store()
        reg.counter("req_total")
        reg.gauge("mem_bytes")
        reg.histogram("lat_seconds")
        for _ in range(2):
            clock.advance(1.0)
            reg.counter("req_total").inc()
            reg.gauge("mem_bytes").set(1.0)
            reg.histogram("lat_seconds").observe(0.01)
            store.scrape_once()
        q = store.query("req_total", window_s=10.0)
        assert q["kind"] == "counter"
        assert {"latest", "delta", "rate_per_s"} <= set(q)
        q = store.query("mem_bytes", window_s=10.0)
        assert q["kind"] == "gauge"
        assert {"latest", "avg", "slope_per_s"} <= set(q)
        q = store.query("lat_seconds", window_s=10.0)
        assert q["kind"] == "histogram"
        assert {"count_delta", "p50", "p99"} <= set(q)
        assert store.query("never_registered")["kind"] is None


# ------------------------------------------------------- counter resets


class TestCounterReset:
    def test_reset_folds_previous_value_into_offset(self):
        reg, clock, store = _store()
        c = reg.counter("req_total")
        for _ in range(5):                        # cumulative 10..50
            clock.advance(1.0)
            c.inc(10)
            store.scrape_once()
        # the rebuild: a fresh counter replaces the old one and
        # restarts from zero
        from paddle_tpu.observability.metrics import Counter
        c2 = Counter("req_total")
        reg.register(c2, replace=True)
        clock.advance(1.0)
        c2.inc(3)
        store.scrape_once()
        # window [2, 6]: adjusted cumulative 20 -> 53, never negative
        assert store.delta("req_total", window_s=4.0) == 33.0
        assert store.stats()["resets"] == 1
        assert store.latest("req_total") == 53.0

    def test_real_serving_metrics_rebuild_mid_soak(self):
        """S1 regression: the exact production shape — ServingMetrics
        is rebuilt (engine restart mid-soak), its counters re-register
        with ``replace=True`` and restart from zero.  The windowed
        delta across the rebuild is the sum of both generations'
        traffic, not a negative number."""
        reg = MetricsRegistry()
        clock = _ManualClock()
        store = TimeSeriesStore(registry=reg, clock=clock)
        sm = ServingMetrics(registry=reg)
        for _ in range(4):
            clock.advance(1.0)
            sm.requests_submitted.inc(5)          # cumulative 5..20
            store.scrape_once()
        sm2 = ServingMetrics(registry=reg)        # the rebuild
        for _ in range(2):
            clock.advance(1.0)
            sm2.requests_submitted.inc(2)         # restarts 2, 4
            store.scrape_once()
        # increase from the first in-window point (cumulative 5) to
        # the last (adjusted cumulative 20 + 4): both generations'
        # traffic counted, NOT 4 - 20
        d = store.delta("serving_requests_submitted_total",
                        window_s=100.0)
        assert d == 19.0
        assert d >= 0.0
        assert store.stats()["resets"] >= 1

    def test_histogram_reset_keeps_window_quantiles_sane(self):
        reg, clock, store = _store()
        h = reg.histogram("lat_seconds", start=1.0, factor=2.0, count=4)
        clock.advance(1.0)
        store.scrape_once()
        h.observe(1.5)
        h.observe(1.5)
        clock.advance(1.0)
        store.scrape_once()
        from paddle_tpu.observability.metrics import Histogram
        h2 = Histogram("lat_seconds", start=1.0, factor=2.0, count=4)
        reg.register(h2, replace=True)
        h2.observe(7.0)                           # total 1 < 2: reset seen
        clock.advance(1.0)
        store.scrape_once()
        # count delta over the whole window: both generations counted
        assert store.delta("lat_seconds", window_s=10.0) == 3.0
        assert store.quantile("lat_seconds", 99, window_s=10.0) > 4.0
        assert store.stats()["resets"] == 1
