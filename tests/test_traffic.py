"""Diurnal traffic generator: determinism, rate shape, cohorts."""
import math

import pytest

from paddle_tpu.serving import TrafficGenerator


class TestDeterminism:
    def test_same_seed_same_trace(self):
        gen = TrafficGenerator(base_rate_per_s=15.0, seed=7)
        a = gen.trace(20.0)
        b = gen.trace(20.0)
        assert len(a) == len(b) > 0
        for x, y in zip(a, b):
            assert x.t == y.t
            assert x.prompt == y.prompt
            assert x.max_new_tokens == y.max_new_tokens
            assert x.cohort == y.cohort

    def test_fresh_generator_reproduces(self):
        a = TrafficGenerator(base_rate_per_s=15.0, seed=7).trace(20.0)
        b = TrafficGenerator(base_rate_per_s=15.0, seed=7).trace(20.0)
        assert [(x.t, tuple(x.prompt)) for x in a] == \
               [(y.t, tuple(y.prompt)) for y in b]

    def test_different_seed_different_trace(self):
        a = TrafficGenerator(base_rate_per_s=15.0, seed=1).trace(20.0)
        b = TrafficGenerator(base_rate_per_s=15.0, seed=2).trace(20.0)
        assert [x.t for x in a] != [y.t for y in b]


class TestRateShape:
    def test_diurnal_curve_peaks_and_troughs(self):
        gen = TrafficGenerator(base_rate_per_s=10.0,
                               diurnal_amplitude=0.8, day_period_s=40.0,
                               seed=0)
        peak = gen.rate_at(10.0)      # sin peak at period/4
        trough = gen.rate_at(30.0)    # sin trough at 3·period/4
        assert peak == pytest.approx(18.0)
        assert trough == pytest.approx(2.0)
        assert gen.rate_at(0.0) == pytest.approx(10.0)
        assert gen.peak_rate() >= peak

    def test_burst_multiplier_windows(self):
        # bursts are (start_s, duration_s, multiplier): [5, 7) here
        gen = TrafficGenerator(base_rate_per_s=10.0,
                               diurnal_amplitude=0.0,
                               bursts=((5.0, 2.0, 3.0),), seed=0)
        assert gen.rate_at(4.9) == pytest.approx(10.0)
        assert gen.rate_at(6.0) == pytest.approx(30.0)
        assert gen.rate_at(7.1) == pytest.approx(10.0)
        assert gen.peak_rate() == pytest.approx(30.0)

    def test_arrival_density_follows_rate(self):
        gen = TrafficGenerator(base_rate_per_s=30.0,
                               diurnal_amplitude=0.9, day_period_s=40.0,
                               seed=3)
        arrivals = gen.trace(40.0)
        # high half-period (sin > 0) vs low half-period
        high = sum(1 for a in arrivals if 0.0 <= a.t < 20.0)
        low = sum(1 for a in arrivals if 20.0 <= a.t < 40.0)
        assert high > 2 * low > 0
        assert all(arrivals[i].t <= arrivals[i + 1].t
                   for i in range(len(arrivals) - 1))

    def test_amplitude_validation(self):
        with pytest.raises(ValueError):
            TrafficGenerator(diurnal_amplitude=1.5)
        with pytest.raises(ValueError):
            TrafficGenerator(prompt_len=(24, 8))


class TestCohorts:
    def test_cohort_arrivals_share_prefix(self):
        gen = TrafficGenerator(base_rate_per_s=25.0, n_cohorts=2,
                               cohort_prefix_len=12,
                               cohort_fraction=1.0, seed=5)
        arrivals = gen.trace(10.0)
        assert arrivals
        prefixes = {}
        for a in arrivals:
            assert a.cohort in (0, 1)
            prefixes.setdefault(a.cohort, set()).add(
                tuple(a.prompt[:12]))
        # every arrival in a cohort carries that cohort's exact prefix
        assert all(len(ps) == 1 for ps in prefixes.values())
        assert len(set().union(*prefixes.values())) == len(prefixes)

    def test_cohort_fraction_zero_means_unique_prompts(self):
        gen = TrafficGenerator(base_rate_per_s=25.0,
                               cohort_fraction=0.0, seed=5)
        arrivals = gen.trace(10.0)
        assert arrivals
        assert all(a.cohort is None for a in arrivals)
        assert len({tuple(a.prompt) for a in arrivals}) == len(arrivals)

    def test_prompt_and_decode_bounds(self):
        gen = TrafficGenerator(base_rate_per_s=25.0, prompt_len=(8, 24),
                               max_new_tokens=(4, 8), vocab_size=512,
                               seed=9)
        arrivals = gen.trace(10.0)
        assert arrivals
        for a in arrivals:
            assert 8 <= len(a.prompt) <= 24
            assert 4 <= a.max_new_tokens <= 8
            assert all(0 <= tok < 512 for tok in a.prompt)

    def test_summary_shape(self):
        gen = TrafficGenerator(base_rate_per_s=10.0, seed=0,
                               bursts=((2.0, 4.0, 2.0),))
        s = gen.summary(20.0)
        assert s["base_rate_per_s"] == 10.0
        assert s["seed"] == 0
        assert s["rate_max"] <= gen.peak_rate()
        assert 0.0 <= s["rate_min"] <= s["rate_mean"] <= s["rate_max"]
        assert math.isfinite(s["rate_mean"])
