"""Training health monitor tests: goodput/MFU accounting, anomaly
detection, and cross-rank metric aggregation (plus the satellite
StatRegistry bridge, checkpoint-save histogram and naming-lint unit
rules)."""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import Dataset
from paddle_tpu.observability import (ClusterAggregator, GoodputMonitor,
                                      HealthMonitor, MetricsRegistry,
                                      RankMetricsPublisher, Tracer,
                                      TrainingHealthError)
from paddle_tpu.observability.compile_watchdog import (default_watchdog,
                                                       watchdog_enabled)
from paddle_tpu.observability.goodput import device_peak_flops, mfu


class Toy(Dataset):
    def __init__(self, n=16, bad_at=None):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 4).astype(np.float32)
        self.y = rng.randint(0, 2, (n,)).astype(np.int64)
        if bad_at is not None:
            self.x[bad_at] = np.inf       # poisons that batch's loss

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _model():
    model = paddle.Model(nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                       nn.Linear(8, 2)))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    return model


class ManualClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- goodput


class TestGoodput:
    def test_peak_flops_table_and_env(self, monkeypatch):
        flops, kind = device_peak_flops()
        assert kind == "cpu" and flops == 1.0e12
        monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "5e13")
        flops, _ = device_peak_flops()
        assert flops == 5e13

    def test_mfu_estimator(self):
        assert mfu(1e12, 0.5, 4e12) == pytest.approx(0.5)
        assert mfu(None, 0.5, 4e12) is None
        assert mfu(1e12, 0.5, None) is None

    def test_breakdown_sums_to_wall_time(self):
        reg = MetricsRegistry()
        default_watchdog().reset()
        mon = GoodputMonitor(registry=reg)
        model = _model()
        with watchdog_enabled():
            t0 = time.perf_counter()
            model.fit(Toy(32), batch_size=4, epochs=2, verbose=0,
                      callbacks=[mon])
            wall = time.perf_counter() - t0
        rep = mon.report()
        assert rep["steps"] == 16
        phase_sum = sum(rep["phases_seconds"].values())
        # phases partition the accounted time exactly...
        assert phase_sum == pytest.approx(rep["total_seconds"], rel=1e-3)
        # ...and the accounted time is the measured fit wall time (±5%:
        # only pre-train setup and the final callback dispatch escape)
        assert rep["total_seconds"] == pytest.approx(wall, rel=0.05)
        # first batch compiled under the watchdog -> nonzero compile
        # phase; the rest is dominated by compute
        assert rep["phases_seconds"]["compile"] > 0
        assert rep["phases_seconds"]["compute"] > 0
        assert 0 < rep["goodput_ratio"] <= 1
        snap = reg.snapshot()
        assert snap["training_goodput_ratio"]["value"]["current"] == \
            pytest.approx(rep["goodput_ratio"])
        phases = {s["labels"]["phase"]: s["value"]["current"]
                  for s in snap["training_step_breakdown_seconds"]["series"]}
        assert phases == pytest.approx(rep["phases_seconds"])
        assert snap["training_step_seconds"]["value"]["count"] == 16

    def test_mfu_published_with_explicit_flops(self):
        reg = MetricsRegistry()
        mon = GoodputMonitor(registry=reg, peak_flops=1e12,
                             flops_per_step=5e9)
        model = _model()
        model.fit(Toy(8), batch_size=4, epochs=1, verbose=0,
                  callbacks=[mon])
        rep = mon.report()
        assert rep["mfu"] is not None and rep["mfu"] > 0
        assert rep["peak_flops"] == 1e12
        assert reg.snapshot()["training_mfu"]["value"]["current"] == \
            pytest.approx(rep["mfu"])

    def test_checkpoint_phase_and_save_histogram(self, tmp_path):
        from paddle_tpu.hapi import CheckpointCallback
        from paddle_tpu.observability import default_registry

        reg = default_registry()
        reg.unregister("checkpoint_save_seconds")
        # goodput monitor FIRST: the checkpoint save then lands in the
        # inter-step gap, exercising the gap re-attribution path
        mon = GoodputMonitor(registry=reg)
        ckpt = CheckpointCallback(save_dir=str(tmp_path), every_n_steps=2)
        model = _model()
        model.fit(Toy(16), batch_size=4, epochs=1, verbose=0,
                  callbacks=[mon, ckpt])
        rep = mon.report()
        assert rep["phases_seconds"]["checkpoint"] > 0
        h = reg.get("checkpoint_save_seconds")
        sync = h.labels(mode="sync")
        assert sync.total == 2                    # steps 2 and 4 of 4
        # checkpoint time is excluded from data_wait, not double-billed
        assert sum(rep["phases_seconds"].values()) == \
            pytest.approx(rep["total_seconds"], rel=1e-3)

    def test_async_save_records_blocking_and_background(self, tmp_path):
        from paddle_tpu.hapi import CheckpointCallback
        from paddle_tpu.observability import default_registry
        from paddle_tpu.resilience import CheckpointManager

        reg = default_registry()
        reg.unregister("checkpoint_save_seconds")
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        ckpt = CheckpointCallback(manager=mgr, every_n_steps=2)
        model = _model()
        model.fit(Toy(8), batch_size=4, epochs=1, verbose=0,
                  callbacks=[ckpt])
        mgr.wait()
        h = reg.get("checkpoint_save_seconds")
        modes = {lv[0] for lv, _ in h._series()}
        assert modes == {"async", "background"}
        # "is async actually overlapping?": the blocking (snapshot)
        # series exists independently from the background write series
        assert h.labels(mode="async").total == 1
        assert h.labels(mode="background").total == 1

    def test_benchmark_step_info_exposes_totals(self):
        from paddle_tpu.profiler.timer import Benchmark

        bm = Benchmark(warmup_steps=0)
        bm.before_reader()
        bm.after_reader()
        bm.step_start()
        bm.step_end(num_samples=4)
        info = bm.step_info()
        assert {"batch_cost_total", "reader_cost_total", "samples",
                "reader_ratio"} <= set(info)
        assert info["samples"] == 4
        assert info["batch_cost_total"] >= 0
        bm.before_reader()
        bm.after_reader()
        assert bm.take_pending_reader_cost() >= 0
        assert bm.take_pending_reader_cost() == 0.0   # drained


# ----------------------------------------------------------------- health


class TestHealthMonitor:
    def _drive(self, mon, seq, dt=0.1):
        """Feed (loss, grad_norm) pairs through the batch hooks with a
        manual clock advancing ``dt`` per step (or per-step dt list)."""
        clk = mon._clock
        for i, (loss, gnorm) in enumerate(seq):
            mon.on_train_batch_begin(i)
            clk.t += dt[i] if isinstance(dt, (list, tuple)) else dt
            logs = {"loss": loss}
            if gnorm is not None:
                logs["grad_norm"] = gnorm
            mon.on_train_batch_end(i, logs)

    def _mon(self, **kw):
        kw.setdefault("registry", MetricsRegistry())
        kw.setdefault("tracer", Tracer())
        kw.setdefault("clock", ManualClock())
        mon = HealthMonitor(**kw)
        mon.set_model(None)
        mon.on_train_begin()
        return mon

    def _anomalies(self, mon):
        c = mon.registry().get("training_anomalies_total")
        return {lv[0]: child.value for lv, child in c._series()} \
            if c else {}

    def test_nan_loss_flagged_exactly_once(self):
        mon = self._mon(action="gauge")
        self._drive(mon, [(1.0, None)] * 5 + [(float("nan"), None)] * 5)
        assert self._anomalies(mon) == {"non_finite_loss": 1}
        assert mon.registry().get("training_healthy").value == 0
        assert not mon.healthy
        # a health::<kind> span landed in the flight recorder
        names = [t["name"] for t in mon.tracer().traces()]
        assert names == ["health::non_finite_loss"]

    def test_recovery_flips_gauge_back(self):
        mon = self._mon(action="gauge", recover_after=2)
        self._drive(mon, [(1.0, None)] * 3 + [(float("inf"), None)]
                    + [(1.0, None)])
        assert mon.registry().get("training_healthy").value == 0
        self._drive(mon, [(1.0, None)])     # second clean step
        assert mon.registry().get("training_healthy").value == 1

    def test_grad_spike_zscore(self):
        mon = self._mon(action="gauge", min_samples=5, window=20)
        rng = np.random.RandomState(0)
        seq = [(1.0, 1.0 + 0.05 * rng.randn()) for _ in range(15)]
        seq.append((1.0, 50.0))
        self._drive(mon, seq)
        assert self._anomalies(mon) == {"grad_spike": 1}
        kinds = [e[0] for e in mon.events]
        assert kinds == ["grad_spike"]

    def test_step_time_outlier(self):
        mon = self._mon(action="gauge", min_samples=5,
                        step_time_zscore=4.0)
        rng = np.random.RandomState(1)
        dts = [0.1 + 0.005 * abs(rng.randn()) for _ in range(15)] + [5.0]
        self._drive(mon, [(1.0, None)] * 16, dt=dts)
        assert self._anomalies(mon) == {"step_time_outlier": 1}

    def test_loss_plateau(self):
        mon = self._mon(action="gauge", plateau_window=5,
                        plateau_min_delta=1e-3)
        losses = [1.0 - 0.05 * i for i in range(10)] + [0.5] * 10
        self._drive(mon, [(l, None) for l in losses])
        assert self._anomalies(mon).get("loss_plateau", 0) >= 1

    def test_action_raise(self):
        mon = self._mon(action="raise")
        with pytest.raises(TrainingHealthError) as ei:
            self._drive(mon, [(float("nan"), None)])
        assert ei.value.kind == "non_finite_loss"

    def test_fit_injected_nan_batch(self):
        """Acceptance: an injected-NaN batch in a real Model.fit is
        flagged exactly once and training_healthy flips to 0."""
        reg = MetricsRegistry()
        mon = HealthMonitor(action="gauge", registry=reg, tracer=Tracer())
        model = _model()
        model.fit(Toy(16, bad_at=8), batch_size=4, epochs=1, verbose=0,
                  callbacks=[mon])
        snap = reg.snapshot()
        series = snap["training_anomalies_total"]["series"]
        by_kind = {s["labels"]["kind"]: s["value"] for s in series}
        # batch 2 goes non-finite, poisons the params, every later loss
        # is NaN too -> still ONE event (the condition stays active)
        assert by_kind["non_finite_loss"] == 1
        assert snap["training_healthy"]["value"]["current"] == 0

    def test_fit_reports_grad_norm(self):
        """HealthMonitor turns on grad-norm logging; the jitted step
        then reports a finite global gradient norm every batch."""
        seen = []

        class Spy(paddle.hapi.Callback):
            def on_train_batch_end(self, step, logs=None):
                seen.append((logs or {}).get("grad_norm"))

        mon = HealthMonitor(action="gauge", registry=MetricsRegistry(),
                            tracer=Tracer())
        model = _model()
        model.fit(Toy(8), batch_size=4, epochs=1, verbose=0,
                  callbacks=[mon, Spy()])
        assert len(seen) == 2
        assert all(g is not None and np.isfinite(g) and g > 0
                   for g in seen)
        assert mon.healthy


# ------------------------------------------------- health-triggered rollback


class _Arrays(Dataset):
    def __init__(self, x, y):
        self.x, self.y = x, y

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _rollback_problem(bad_batches=(5,), batch=4, n=32):
    """(poisoned dataset, reference dataset) — the reference simply has
    the poisoned batches' samples removed, which is exactly what a
    rollback + skipped-window run should be equivalent to."""
    rng = np.random.RandomState(7)
    y = rng.randint(0, 2, (n,)).astype(np.int64)
    x = (rng.randn(n, 4) * 0.3 + y[:, None] * 2.0).astype(np.float32)
    bad = x.copy()
    keep = np.ones(n, bool)
    for b in bad_batches:
        bad[b * batch:(b + 1) * batch] = np.nan
        keep[b * batch:(b + 1) * batch] = False
    return _Arrays(bad, y), _Arrays(x[keep], y[keep])


def _rb_model(seed=11):
    paddle.seed(seed)
    model = paddle.Model(nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                       nn.Linear(8, 2)))
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    return model


class _Losses(paddle.hapi.Callback):
    def __init__(self):
        super().__init__()
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(logs["loss"])


def _rollback_count(reason):
    from paddle_tpu.observability import default_registry

    fam = default_registry().get("training_rollbacks_total")
    return fam.labels(reason=reason).value if fam else 0


class TestHealthRollback:
    def test_nan_batch_rolls_back_once_and_skips_window(self, tmp_path):
        """Acceptance: an injected-NaN batch triggers exactly one
        rollback to the last good checkpoint
        (training_rollbacks_total{reason="non_finite_loss"} == 1) and
        the continued loss curve past the skipped window equals a run
        that never saw the poisoned batch."""
        from paddle_tpu.hapi import CheckpointCallback
        from paddle_tpu.resilience import CheckpointManager

        data, ref_data = _rollback_problem(bad_batches=(5,))
        ref_rec = _Losses()
        _rb_model().fit(ref_data, batch_size=4, epochs=1, shuffle=False,
                        verbose=0,
                        callbacks=[ref_rec,
                                   HealthMonitor(action="gauge")])
        assert len(ref_rec.losses) == 7

        before = _rollback_count("non_finite_loss")
        rec = _Losses()
        mon = HealthMonitor(action="rollback")
        ckdir = str(tmp_path / "ck")
        _rb_model().fit(data, batch_size=4, epochs=1, shuffle=False,
                        verbose=0,
                        callbacks=[rec, mon,
                                   CheckpointCallback(ckdir,
                                                      every_n_steps=1)])
        assert len(rec.losses) == 8
        assert not np.isfinite(rec.losses[5])       # the poisoned step
        assert _rollback_count("non_finite_loss") == before + 1
        assert mon.rollbacks == 1
        assert mon.healthy                           # recovered
        # pre-window and post-window segments line up with the
        # never-saw-that-batch reference, step for step
        np.testing.assert_allclose(rec.losses[:5], ref_rec.losses[:5],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(rec.losses[6:], ref_rec.losses[5:],
                                   rtol=1e-5, atol=1e-6)
        # the skipped window is durable in the newest manifest
        _, _, manifest = CheckpointManager(ckdir).restore()
        windows = manifest["extra"]["skipped_windows"]
        assert len(windows) == 1
        w = windows[0]
        assert w["reason"] == "non_finite_loss"
        assert (w["first_step"], w["last_step"]) == (5, 5)
        assert w["restored_global_step"] == 5
        # the rollback left a supervisor::rollback span in the recorder
        from paddle_tpu.observability import default_tracer

        names = [t["name"] for t in default_tracer().traces()]
        assert "supervisor::rollback" in names

    @pytest.mark.faultinject
    def test_kill_right_after_rollback_resumes_past_window(self,
                                                           tmp_path):
        """The skipped window is committed the instant the rollback
        happens: a process killed immediately after must resume PAST
        the poisoned batch — never replay it, never re-anomaly."""
        from paddle_tpu.hapi import CheckpointCallback
        from paddle_tpu.resilience import (CheckpointManager, FaultSpec,
                                           SimulatedCrash,
                                           injected_faults)

        data, ref_data = _rollback_problem(bad_batches=(5,))
        ref_rec = _Losses()
        _rb_model().fit(ref_data, batch_size=4, epochs=1, shuffle=False,
                        verbose=0,
                        callbacks=[ref_rec,
                                   HealthMonitor(action="gauge")])

        ckdir = str(tmp_path / "ck")
        rec_a = _Losses()
        with injected_faults(FaultSpec("hapi.train_step", "kill",
                                       occurrence=6)):
            with pytest.raises(SimulatedCrash):
                _rb_model().fit(
                    data, batch_size=4, epochs=1, shuffle=False,
                    verbose=0,
                    callbacks=[rec_a, HealthMonitor(action="rollback"),
                               CheckpointCallback(ckdir,
                                                  every_n_steps=1)])
        assert len(rec_a.losses) == 6       # killed at the bad step

        rec_b = _Losses()
        mon_b = HealthMonitor(action="rollback")
        _rb_model(seed=99).fit(
            data, batch_size=4, epochs=1, shuffle=False, verbose=0,
            callbacks=[rec_b, mon_b,
                       CheckpointCallback(ckdir, every_n_steps=1)],
            resume_from=ckdir)
        assert len(rec_b.losses) == 2       # batches 6 and 7 only
        assert mon_b.events == []           # the bad batch never replayed
        np.testing.assert_allclose(rec_b.losses, ref_rec.losses[5:],
                                   rtol=1e-5, atol=1e-6)
        # the window survives the relaunch's own manifests
        _, _, manifest = CheckpointManager(ckdir).restore()
        assert len(manifest["extra"]["skipped_windows"]) == 1

    def test_rollback_without_checkpoint_callback_raises(self):
        data, _ = _rollback_problem(bad_batches=(2,), n=16)
        with pytest.raises(TrainingHealthError) as ei:
            _rb_model().fit(data, batch_size=4, epochs=1, shuffle=False,
                            verbose=0,
                            callbacks=[HealthMonitor(action="rollback")])
        assert ei.value.kind == "non_finite_loss"
        assert "CheckpointCallback" in str(ei.value)

    def test_max_rollbacks_escalates(self, tmp_path):
        """Two poisoned batches with max_rollbacks=1: the first rolls
        back, the second escalates — a run that keeps needing rewinds
        must die loudly, not thrash forever."""
        from paddle_tpu.hapi import CheckpointCallback

        data, _ = _rollback_problem(bad_batches=(2, 5))
        mon = HealthMonitor(action="rollback", max_rollbacks=1)
        with pytest.raises(TrainingHealthError):
            _rb_model().fit(
                data, batch_size=4, epochs=1, shuffle=False, verbose=0,
                callbacks=[mon,
                           CheckpointCallback(str(tmp_path / "ck"),
                                              every_n_steps=1)])
        assert mon.rollbacks == 2

    def test_grad_spike_requests_rollback(self):
        """Unit: a grad-norm outlier under action='rollback' files a
        rollback request on the model (the fit loop executes it)."""
        class Stub:
            _rollback_request = None

        mon = HealthMonitor(action="rollback", min_samples=5, window=20,
                            registry=MetricsRegistry(), tracer=Tracer(),
                            clock=ManualClock())
        mon.set_model(Stub())
        mon.on_train_begin()
        rng = np.random.RandomState(0)
        for i in range(15):
            mon.on_train_batch_begin(i)
            mon.on_train_batch_end(
                i, {"loss": 1.0, "grad_norm": 1.0 + 0.05 * rng.randn()})
        mon.on_train_batch_begin(15)
        mon.on_train_batch_end(15, {"loss": 1.0, "grad_norm": 50.0})
        req = mon.model._rollback_request
        assert req is not None and req["reason"] == "grad_spike"
        assert mon.rollbacks == 1


# ------------------------------------------------------ cross-rank merge


def _rank_registry(rank, step_time):
    reg = MetricsRegistry()
    h = reg.histogram("training_step_seconds")
    for _ in range(8):
        h.observe(step_time)
    reg.counter("steps_done_total").inc(8)
    reg.gauge("training_goodput_ratio").set(0.9 - 0.1 * rank)
    return reg


class TestCrossRankAggregation:
    STEP_TIMES = {0: 0.10, 1: 0.12, 2: 1.0}    # rank 2 is the straggler

    def _publish_from_threads(self, master):
        """3 simulated ranks, each a thread with its own TCPStore
        client, publish their registry snapshots."""
        errs = []

        def worker(rank):
            try:
                from paddle_tpu.distributed.store import TCPStore

                st = TCPStore(port=master.port, is_master=False,
                              world_size=3)
                reg = _rank_registry(rank, self.STEP_TIMES[rank])
                RankMetricsPublisher(st, rank, registry=reg).publish()
            except Exception as e:      # pragma: no cover
                errs.append((rank, e))

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert errs == []

    def test_merged_exposition_and_skew(self):
        from paddle_tpu.distributed.store import TCPStore

        master = TCPStore(is_master=True, world_size=3)
        self._publish_from_threads(master)
        local = MetricsRegistry()
        agg = ClusterAggregator(master, world_size=3, registry=local)
        text = agg.expose_prometheus()
        # every series carries its rank label
        for r in range(3):
            assert f'steps_done_total{{rank="{r}"}} 8' in text
        assert 'training_goodput_ratio{rank="1"} 0.8' in text
        # histograms travel as summaries
        assert 'training_step_seconds{rank="2",quantile="0.5"} 1' in text
        assert 'training_step_seconds_count{rank="0"} 8' in text
        # straggler skew: rank 2 at 1.0s vs rank 0 at 0.10s
        assert agg.last_skew_s == pytest.approx(0.9, rel=1e-6)
        assert local.get("training_step_time_skew_seconds").value == \
            pytest.approx(0.9, rel=1e-6)
        assert "training_step_time_skew_seconds 0.9" in text
        assert "cluster_ranks_reporting 3" in text
        snap = agg.merged_snapshot(collect=False)
        assert set(snap["ranks"]) == {"0", "1", "2"}
        assert snap["step_time_skew_seconds"] == \
            pytest.approx(0.9, rel=1e-6)

    def test_killed_rank_ages_out(self):
        from paddle_tpu.distributed.store import TCPStore

        master = TCPStore(is_master=True, world_size=3)
        clk = ManualClock(t=1000.0)
        pubs = [RankMetricsPublisher(
                    master, r, registry=_rank_registry(r, 0.1), clock=clk)
                for r in range(3)]
        for p in pubs:
            p.publish()
        agg = ClusterAggregator(master, world_size=3, stale_after_s=30.0,
                                registry=MetricsRegistry(), clock=clk)
        assert set(agg.collect()) == {0, 1, 2}
        # rank 2 dies; 0 and 1 keep publishing past the staleness window
        clk.t += 60.0
        pubs[0].publish()
        pubs[1].publish()
        fresh = agg.collect()
        assert set(fresh) == {0, 1}
        assert agg.stale_ranks == [2]
        text = agg.expose_prometheus(collect=False)
        assert 'rank="2"' not in text      # aged out, not poisoning
        assert 'steps_done_total{rank="0"} 8' in text
        assert "cluster_ranks_stale 1" in text

    def test_missing_rank_never_published(self):
        from paddle_tpu.distributed.store import TCPStore

        master = TCPStore(is_master=True, world_size=2)
        RankMetricsPublisher(master, 0,
                             registry=_rank_registry(0, 0.1)).publish()
        agg = ClusterAggregator(master, world_size=2,
                                registry=MetricsRegistry())
        assert set(agg.collect()) == {0}
        assert agg.missing_ranks == [1]
        assert agg.last_skew_s is None      # one rank -> no skew

    def test_fleet_metrics_endpoint(self):
        """Acceptance: rank 0's /metrics serves the merged fleet view."""
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.observability import start_telemetry_server

        master = TCPStore(is_master=True, world_size=3)
        self._publish_from_threads(master)
        local = MetricsRegistry()
        agg = ClusterAggregator(master, world_size=3, registry=local)
        srv = start_telemetry_server(port=0, registry=local,
                                     tracer=Tracer(), aggregator=agg)
        try:
            with urllib.request.urlopen(srv.url + "/metrics",
                                        timeout=10) as r:
                body = r.read().decode()
            assert 'steps_done_total{rank="1"} 8' in body
            assert "training_step_time_skew_seconds" in body
            with urllib.request.urlopen(srv.url + "/varz",
                                        timeout=10) as r:
                varz = json.loads(r.read().decode())
            assert set(varz["cluster"]["ranks"]) == {"0", "1", "2"}
        finally:
            srv.stop()

    def test_publisher_thread_republishes(self):
        from paddle_tpu.distributed.store import TCPStore

        master = TCPStore(is_master=True, world_size=1)
        pub = RankMetricsPublisher(master, 0,
                                   registry=_rank_registry(0, 0.1))
        with pub.start(interval_s=0.01):
            deadline = time.time() + 10
            while pub.published < 3 and time.time() < deadline:
                time.sleep(0.01)
        assert pub.published >= 3
        payload = json.loads(master.get("metrics/rank_0"))
        assert payload["rank"] == 0
        assert "training_step_seconds" in payload["metrics"]


# -------------------------------------------------------- stat bridge


class TestStatBridge:
    def test_stats_appear_on_scrape(self):
        from paddle_tpu.utils.monitor import StatRegistry, bridge_to_metrics

        sr = StatRegistry()
        mr = MetricsRegistry()
        collector = bridge_to_metrics(sr, mr)
        assert mr.snapshot() == {}          # nothing to bridge yet
        sr.add("pool_alloc", 5)
        sr.add("pool_alloc", -2)            # peak 5, current 3
        sr.add("host_buffers", 1)
        snap = mr.snapshot()
        series = {s["labels"]["name"]: s["value"]
                  for s in snap["runtime_stat"]["series"]}
        assert series["pool_alloc"]["current"] == 3
        assert series["pool_alloc"]["peak"] == 5
        assert series["host_buffers"]["current"] == 1
        text = mr.expose_prometheus()
        assert 'runtime_stat{name="pool_alloc"} 3' in text
        assert 'runtime_stat_peak{name="pool_alloc"} 5' in text
        mr.remove_collector(collector)

    def test_default_bridge_installed(self):
        from paddle_tpu.observability import default_registry
        from paddle_tpu.utils import stat_add, stat_reset

        stat_reset()
        stat_add("bridge_check", 7)
        try:
            snap = default_registry().snapshot()
            series = {s["labels"]["name"]: s["value"]
                      for s in snap["runtime_stat"]["series"]}
            assert series["bridge_check"]["current"] == 7
        finally:
            stat_reset()

    def test_broken_collector_does_not_break_scrape(self):
        mr = MetricsRegistry()
        mr.gauge("ok_gauge").set(1)

        def broken():
            raise RuntimeError("bridge died")

        mr.add_collector(broken)
        snap = mr.snapshot()                # must not raise
        assert snap["ok_gauge"]["value"]["current"] == 1
        mr.remove_collector(broken)


# ------------------------------------------------------ naming lint


class TestUnitSuffixLint:
    def _tool(self):
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "tools", "check_metric_names.py")
        spec = importlib.util.spec_from_file_location(
            "check_metric_names", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    # the repo-wide sweep now runs ONCE in the consolidated suite:
    # tests/test_static_analysis.py::TestTier1Suite

    def test_unit_suffix_rules(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "from paddle_tpu.observability import Gauge, Histogram\n"
            "a = Histogram('request_latency_ms')\n"   # abbreviated unit
            "b = Histogram('step_time')\n"            # no unit suffix
            "c = Gauge('drain_s')\n"                  # abbreviated unit
            "d = Gauge('queue_depth')\n"              # unitless gauge: ok
            "e = Histogram('load_seconds')\n"         # canonical: ok
            "f = Gauge('mem_bytes')\n")               # canonical: ok
        violations = self._tool().check(root=str(tmp_path))
        text = "\n".join(violations)
        assert "request_latency_ms" in text
        assert "step_time" in text and "canonical unit suffix" in text
        assert "drain_s" in text
        assert "queue_depth" not in text
        assert "load_seconds" not in text
        assert "mem_bytes" not in text
        assert len(violations) == 3
