"""Tests: utils (monitor/logging), profiler summary, sparse, custom ops."""
import logging

import numpy as np
import pytest

import paddle_tpu as paddle


class TestMonitor:
    def test_stat_registry(self):
        from paddle_tpu.utils import StatRegistry

        r = StatRegistry()
        assert r.add("mem", 100) == 100
        assert r.add("mem", -40) == 60
        assert r.peak("mem") == 100
        assert r.get("mem") == 60
        r.reset("mem")
        assert r.get("mem") == 0

    def test_global_stats(self):
        from paddle_tpu.utils import stat_add, stat_get, stat_reset

        stat_reset()
        stat_add("steps")
        stat_add("steps")
        assert stat_get("steps") == 2

    def test_device_memory_stats_shape(self):
        from paddle_tpu.utils import device_memory_stats

        stats = device_memory_stats()
        assert isinstance(stats, dict)


class TestLogging:
    def test_rank_in_records(self, capsys):
        import os

        from paddle_tpu.utils.log_util import get_logger

        os.environ["PADDLE_TRAINER_ID"] = "3"
        try:
            log = get_logger("pt_test", level=logging.INFO)
            log.info("hello")
            err = capsys.readouterr().err
            assert "[rank 3]" in err and "hello" in err
        finally:
            del os.environ["PADDLE_TRAINER_ID"]

    def test_vlog_gated(self, capsys):
        from paddle_tpu.utils.log_util import vlog

        vlog(3, "should not appear")
        assert "should not appear" not in capsys.readouterr().err


class TestProfilerSummary:
    def test_summary_table(self):
        import time

        from paddle_tpu.profiler.profiler import Profiler, RecordEvent

        p = Profiler(with_device=False)
        p.start()
        for _ in range(3):
            with RecordEvent("op_a"):
                time.sleep(0.002)
        with RecordEvent("op_b"):
            time.sleep(0.001)
        p.stop()
        table = p.summary()
        lines = table.splitlines()
        assert "Name" in lines[0] and "Calls" in lines[0]
        assert any("op_a" in l and " 3 " in l for l in lines)
        assert any("op_b" in l for l in lines)
        # op_a total > op_b total => sorted first
        assert lines[1].startswith("op_a")

    def test_chrome_export(self, tmp_path):
        import json

        from paddle_tpu.profiler.profiler import Profiler, RecordEvent

        p = Profiler(with_device=False)
        p.start()
        with RecordEvent("evt"):
            pass
        p.stop()
        out = tmp_path / "trace.json"
        p.export(str(out))
        data = json.loads(out.read_text())
        events = data["traceEvents"] if isinstance(data, dict) else data
        assert any(e.get("name") == "evt" for e in events)


class TestSparse:
    def test_coo_roundtrip(self):
        import paddle_tpu.sparse as sp

        dense = np.zeros((4, 5), np.float32)
        dense[0, 1] = 2.0
        dense[3, 4] = -1.0
        idx = np.array([[0, 3], [1, 4]])
        coo = sp.sparse_coo_tensor(idx, np.array([2.0, -1.0], np.float32),
                                   shape=(4, 5))
        assert coo.nnz == 2
        np.testing.assert_allclose(np.asarray(coo.to_dense().data), dense)

    def test_coo_matmul(self):
        import paddle_tpu.sparse as sp

        rng = np.random.RandomState(0)
        dense = (rng.rand(6, 4) > 0.7).astype(np.float32) * rng.rand(6, 4)
        rows, cols = np.nonzero(dense)
        coo = sp.sparse_coo_tensor(np.stack([rows, cols]),
                                   dense[rows, cols].astype(np.float32),
                                   shape=dense.shape)
        b = rng.randn(4, 3).astype(np.float32)
        out = sp.matmul(coo, b)
        np.testing.assert_allclose(np.asarray(out.data), dense @ b,
                                   atol=1e-5)

    def test_csr_conversion(self):
        import paddle_tpu.sparse as sp

        dense = np.array([[0, 1, 0], [2, 0, 3]], np.float32)
        rows, cols = np.nonzero(dense)
        coo = sp.sparse_coo_tensor(np.stack([rows, cols]),
                                   dense[rows, cols], shape=dense.shape)
        csr = coo.to_sparse_csr()
        np.testing.assert_allclose(np.asarray(csr.to_dense().data), dense)
        np.testing.assert_array_equal(np.asarray(csr.crows().data),
                                      [0, 1, 3])

    def test_sparse_add(self):
        import paddle_tpu.sparse as sp

        a = sp.sparse_coo_tensor([[0, 1], [0, 1]],
                                 np.array([1.0, 2.0], np.float32), (2, 2))
        b = sp.sparse_coo_tensor([[0, 1], [0, 0]],
                                 np.array([5.0, 7.0], np.float32), (2, 2))
        out = sp.add(a, b)
        np.testing.assert_allclose(np.asarray(out.to_dense().data),
                                   [[6.0, 0.0], [7.0, 2.0]])


class TestCustomOp:
    def test_register_and_call(self):
        import jax.numpy as jnp

        from paddle_tpu.incubate import build_op

        my = build_op("test_relu6", lambda x: jnp.clip(x, 0.0, 6.0))
        out = my(paddle.to_tensor(np.array([-1.0, 3.0, 9.0], np.float32)))
        np.testing.assert_allclose(np.asarray(out.data), [0.0, 3.0, 6.0])

    def test_autograd_through_custom_op(self):
        import jax.numpy as jnp

        from paddle_tpu.incubate import build_op

        sq = build_op("test_square", lambda x: x * x)
        x = paddle.to_tensor(np.array([2.0, -3.0], np.float32))
        x.stop_gradient = False
        y = sq(x).sum()
        y.backward()
        np.testing.assert_allclose(np.asarray(x.grad.data), [4.0, -6.0])

    def test_custom_vjp(self):
        import jax.numpy as jnp

        from paddle_tpu.incubate import custom_op

        # forward returns (out, residuals); backward gets (res, cot)
        op = custom_op.custom_op(
            "test_scaled_id",
            forward=lambda x: (x * 3.0, None),
            backward=lambda res, g: (g * 100.0,))  # deliberately wrong grad
        x = paddle.to_tensor(np.array([1.0], np.float32))
        x.stop_gradient = False
        op(x).sum().backward()
        # the CUSTOM rule must win over autodiff (3.0)
        np.testing.assert_allclose(np.asarray(x.grad.data), [100.0])

    def test_builder_style(self):
        import jax.numpy as jnp

        from paddle_tpu.incubate import CustomOpBuilder

        op = (CustomOpBuilder("test_cube").set_forward(lambda x: x ** 3)
              .register())
        out = op(paddle.to_tensor(np.array([2.0], np.float32)))
        np.testing.assert_allclose(np.asarray(out.data), [8.0])
