"""Repo tooling: lints, sweeps, profiles.  The static-analysis suite
lives in :mod:`tools.analysis`; the ``check_*.py`` modules at this
level are compatibility shims over its passes."""
