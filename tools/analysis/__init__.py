"""Unified static-analysis framework for the repo's tier-1 lints.

One :class:`~tools.analysis.core.Project` loader + parse cache, one
:class:`~tools.analysis.core.Finding` record, uniform ``# lint-ok:
<rule> <reason>`` suppressions and per-rule baseline files, and a
``python -m tools.analysis`` CLI that runs every registered pass.

Passes (see :mod:`tools.analysis.passes`):

====================== ==============================================
rule id                invariant
====================== ==============================================
atomic-writes          durable writes go through resilience.atomic
metric-names           Prometheus naming conventions
fault-sites            every fault site exercised by a test
collective-instrumented every public collective flight-recorded
bounded-retries        blocking retry loops carry a bound
excepts                no silent broad-exception swallows
lock-discipline        guarded-by attrs accessed under their lock;
                       no lock-order cycles; no split check-then-act
trace-purity           jitted call graphs free of clocks/randomness/
                       host syncs/global mutation
====================== ==============================================
"""
from tools.analysis.core import (Finding, Project, REGISTRY, register,
                                 run_all, run_pass, load_baseline,
                                 write_baseline, main)

__all__ = ["Finding", "Project", "REGISTRY", "register", "run_all",
           "run_pass", "load_baseline", "write_baseline", "main"]
