import os
import sys

# direct script execution (`python tools/analysis`) lacks the repo
# root on sys.path; `python -m tools.analysis` from the repo root is
# the documented form and already has it
_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                     os.pardir, os.pardir))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analysis.core import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
