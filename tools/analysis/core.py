"""Shared static-analysis core: one loader, one finding record, one
suppression + baseline scheme for every lint in the repo.

Six generations of one-off ``tools/check_*.py`` scripts each re-walked
the tree with a private loader, a private finding format and a private
allowlist dialect.  This module is the consolidation: a
:class:`Project` loads and parses every file ONCE (all passes share
the cache), passes return :class:`Finding` records, and the runner
applies two uniform escape hatches —

- **suppression**: ``# lint-ok: <rule> <reason>`` on the flagged line
  (or the line directly above, for lines with no room) silences that
  one finding.  The reason is mandatory; a naked ``lint-ok:`` marker
  suppresses nothing.
- **baseline**: ``tools/analysis/baselines/<rule>.txt`` lists
  grandfathered findings as ``<file>: <message>`` lines (no line
  numbers — baselines must survive unrelated edits).  ``python -m
  tools.analysis --write-baseline <rule>`` regenerates one.

``python -m tools.analysis`` runs every registered pass and exits
nonzero on any finding that is neither suppressed nor baselined.
"""
from __future__ import annotations

import ast
import os
import re
import sys
import time

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 os.pardir, os.pardir))
BASELINE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "baselines")

_LINT_OK = re.compile(r"#\s*lint-ok:\s*(?P<rule>[A-Za-z0-9_-]+)\s+\S")


class Finding:
    """One diagnostic: where (repo-relative file, 1-based line), which
    rule, and a human message.  ``baseline_key`` intentionally omits
    the line number so a baseline survives edits elsewhere in the
    file."""

    __slots__ = ("file", "line", "rule", "message")

    def __init__(self, file, line, rule, message):
        self.file = file
        self.line = int(line)
        self.rule = rule
        self.message = message

    def __repr__(self):
        return f"Finding({str(self)!r})"

    def __str__(self):
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"

    def __eq__(self, other):
        return isinstance(other, Finding) and (
            (self.file, self.line, self.rule, self.message)
            == (other.file, other.line, other.rule, other.message))

    def __hash__(self):
        return hash((self.file, self.line, self.rule, self.message))

    @property
    def baseline_key(self):
        return f"{self.file}: {self.message}"


class SourceModule:
    """One parsed file: raw text, split lines and a lazily-built AST,
    cached so eight passes cost one parse."""

    def __init__(self, path, rel):
        self.path = path
        self.rel = rel                      # repo-relative, posix slashes
        with open(path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self._tree = None
        self._parse_error = None

    @property
    def tree(self):
        """The module AST, or ``None`` on a syntax error (passes skip
        unparseable files; the file would fail import long before any
        lint matters)."""
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.path)
            except SyntaxError as e:
                self._parse_error = e
        return self._tree

    def line_at(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, rule, lineno):
        """True when the finding line — or the contiguous block of
        comment-only lines directly above it — carries
        ``# lint-ok: <rule> <reason>``."""
        def matches(text):
            m = _LINT_OK.search(text)
            return bool(m and m.group("rule") in (rule, "all"))

        if matches(self.line_at(lineno)):
            return True
        ln = lineno - 1
        while ln >= 1 and self.line_at(ln).strip().startswith("#"):
            if matches(self.line_at(ln)):
                return True
            ln -= 1
        return False


class Project:
    """The analysis universe: every ``.py`` under ``package_root``
    (default ``paddle_tpu/``), loaded once, plus the raw text of
    ``tests/`` for coverage-style passes.  Both roots are overridable
    so self-tests can point a pass at a fixture tree."""

    def __init__(self, package_root=None, tests_root=None,
                 repo_root=None, scope=None):
        self.repo_root = os.path.abspath(repo_root or REPO_ROOT)
        self.package_root = os.path.abspath(
            package_root or os.path.join(self.repo_root, "paddle_tpu"))
        self.tests_root = os.path.abspath(
            tests_root or os.path.join(self.repo_root, "tests"))
        #: repo-relative files to REPORT on (``--changed-only``).
        #: None = everything.  Analysis stays whole-program — call
        #: graphs, lock-order edges and axis universes are built from
        #: every module regardless — only findings (and the per-module
        #: loops of passes that opt in via :meth:`scoped_modules`) are
        #: restricted, so a changed-only run can never report
        #: differently from the full run on the files it covers.
        self.scope = None if scope is None else set(scope)
        self._modules = None
        self._tests_blob = None

    def _rel(self, path):
        # repo-relative when under the repo, package-dir-relative for
        # fixture trees living in a tmpdir
        for base in (self.repo_root, os.path.dirname(self.package_root)):
            if path.startswith(base + os.sep):
                return os.path.relpath(path, base).replace(os.sep, "/")
        return os.path.basename(path)

    def modules(self):
        """All package modules, loaded+cached on first call, sorted by
        path so every pass sees one deterministic order."""
        if self._modules is None:
            found = []
            for dirpath, _, files in os.walk(self.package_root):
                for name in sorted(files):
                    if name.endswith(".py"):
                        full = os.path.join(dirpath, name)
                        found.append(SourceModule(full, self._rel(full)))
            found.sort(key=lambda m: m.rel)
            self._modules = found
        return self._modules

    def scoped_modules(self):
        """The modules a per-module pass needs to analyze: everything
        normally, only the changed set under ``--changed-only``.  Safe
        ONLY for passes whose findings are a function of one module at
        a time; cross-module passes keep iterating :meth:`modules` and
        rely on the runner's finding-level scope filter."""
        if self.scope is None:
            return self.modules()
        return [m for m in self.modules() if m.rel in self.scope]

    def in_scope(self, rel):
        return self.scope is None or rel in self.scope

    def module(self, rel_suffix):
        """The first module whose repo-relative path ends with
        ``rel_suffix`` (e.g. ``distributed/collective.py``), or None."""
        for mod in self.modules():
            if mod.rel.endswith(rel_suffix):
                return mod
        return None

    def tests_blob(self):
        """All test sources concatenated — coverage passes only need
        'does this literal appear anywhere under tests/'."""
        if self._tests_blob is None:
            chunks = []
            if os.path.isdir(self.tests_root):
                for dirpath, _, files in os.walk(self.tests_root):
                    for name in sorted(files):
                        if name.endswith(".py"):
                            with open(os.path.join(dirpath, name),
                                      encoding="utf-8") as f:
                                chunks.append(f.read())
            self._tests_blob = "\n".join(chunks)
        return self._tests_blob


# --------------------------------------------------------- pass registry

#: rule-id -> pass callable ``(Project) -> [Finding]``; populated by
#: :func:`register` at import of :mod:`tools.analysis.passes`
REGISTRY = {}


def register(rule, doc=""):
    """Decorator: install ``fn(project) -> [Finding]`` under ``rule``."""
    def deco(fn):
        fn.rule = rule
        fn.doc = doc or (fn.__doc__ or "").strip().splitlines()[0]
        REGISTRY[rule] = fn
        return fn
    return deco


def baseline_path(rule, baseline_dir=None):
    return os.path.join(baseline_dir or BASELINE_DIR, f"{rule}.txt")


def load_baseline(rule, baseline_dir=None):
    """The grandfathered ``baseline_key`` set for one rule (empty when
    no baseline file exists — the normal, fully-clean state)."""
    path = baseline_path(rule, baseline_dir)
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        return {line.strip() for line in f
                if line.strip() and not line.startswith("#")}


def write_baseline(rule, findings, baseline_dir=None):
    """Regenerate one rule's baseline from its current raw findings.
    An empty finding list removes the file: no findings, no baseline."""
    path = baseline_path(rule, baseline_dir)
    keys = sorted({f.baseline_key for f in findings})
    if not keys:
        if os.path.exists(path):
            os.remove(path)
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    body = ("# grandfathered findings for rule '%s'\n"
            "# regenerate: python -m tools.analysis --write-baseline %s\n"
            % (rule, rule)) + "\n".join(keys) + "\n"
    # plain write is fine here: this file is repo-tracked tool state,
    # regenerated on demand, not runtime-durable data
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(body)
    os.replace(tmp, path)
    return path


def apply_suppressions(project, findings):
    """Drop findings whose line carries a matching ``lint-ok`` marker."""
    by_rel = {m.rel: m for m in project.modules()}
    kept = []
    for f in findings:
        mod = by_rel.get(f.file)
        if mod is not None and mod.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    return kept


def run_pass(fn, project, baseline_dir=None):
    """One pass end to end: run, suppress, split vs baseline.  Returns
    ``(new_findings, baselined_findings, elapsed_s)``."""
    t0 = time.perf_counter()
    raw = fn(project)
    if project.scope is not None:
        raw = [f for f in raw if project.in_scope(f.file)]
    kept = apply_suppressions(project, raw)
    base = load_baseline(fn.rule, baseline_dir)
    new = [f for f in kept if f.baseline_key not in base]
    old = [f for f in kept if f.baseline_key in base]
    return new, old, time.perf_counter() - t0


def run_all(project=None, rules=None, baseline_dir=None):
    """Run every registered pass (or the named subset).  Returns a
    report dict; ``report['new']`` nonempty means the suite fails."""
    # ensure the pass modules have registered themselves
    from tools.analysis import passes as _passes  # noqa: F401

    project = project or Project()
    selected = rules or list(REGISTRY)
    report = {"passes": {}, "new": [], "baselined": [],
              "files_scanned": len(project.modules())}
    t0 = time.perf_counter()
    for rule in selected:
        fn = REGISTRY[rule]
        new, old, dt = run_pass(fn, project, baseline_dir)
        report["passes"][rule] = {
            "new": len(new), "baselined": len(old), "seconds": dt}
        report["new"].extend(new)
        report["baselined"].extend(old)
    report["seconds"] = time.perf_counter() - t0
    return report


def changed_files(repo_root=None):
    """Repo-relative ``.py`` paths touched vs HEAD (staged, unstaged
    and untracked).  Raises RuntimeError when git is unavailable —
    ``--changed-only`` is a developer convenience, not a CI mode."""
    import subprocess

    root = os.path.abspath(repo_root or REPO_ROOT)
    out = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(args, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"--changed-only needs git: {e}") from e
        if proc.returncode != 0:
            raise RuntimeError(
                f"--changed-only: {' '.join(args)} failed: "
                f"{proc.stderr.strip()}")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip().endswith(".py"))
    return out


def main(argv=None):
    import argparse

    from tools.analysis import passes as _passes  # noqa: F401

    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="run the repo's static-analysis suite")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="RULE", choices=sorted(REGISTRY),
                    help="run only this rule (repeatable)")
    ap.add_argument("--root", default=None,
                    help="package root to scan (default: paddle_tpu/)")
    ap.add_argument("--write-baseline", action="append", default=None,
                    metavar="RULE",
                    help="regenerate the baseline for RULE from current "
                         "findings, then exit 0")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print grandfathered findings")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only in files changed vs "
                         "HEAD (git diff + untracked); analysis stays "
                         "whole-program, so results match the full "
                         "run on the covered files.  Developer "
                         "convenience — tier-1 runs full-repo.")
    args = ap.parse_args(argv)

    if args.list:
        for rule in sorted(REGISTRY):
            print(f"{rule:26s} {REGISTRY[rule].doc}")
        return 0

    scope = None
    if args.changed_only:
        try:
            scope = changed_files()
        except RuntimeError as e:
            print(f"tools.analysis: {e}", file=sys.stderr)
            return 2
        if not scope:
            print("tools.analysis: OK — --changed-only with no "
                  "changed .py files, nothing to check")
            return 0
        print(f"tools.analysis: scoped to {len(scope)} changed "
              f"file(s)")

    project = Project(package_root=args.root, scope=scope)

    if args.write_baseline:
        for rule in args.write_baseline:
            fn = REGISTRY[rule]
            raw = apply_suppressions(project, fn(project))
            path = write_baseline(rule, raw)
            print(f"[{rule}] baseline: {len(raw)} finding(s) -> {path}")
        return 0

    report = run_all(project, rules=args.rule)
    for f in report["baselined"] if args.show_baselined else []:
        print(f"BASELINED {f}")
    for f in report["new"]:
        print(f"{f}", file=sys.stderr)
    n_pass = len(report["passes"])
    if report["new"]:
        print(f"tools.analysis: {len(report['new'])} new finding(s) "
              f"across {n_pass} passes "
              f"({report['files_scanned']} files, "
              f"{report['seconds']:.2f}s)", file=sys.stderr)
        return 1
    extra = (f", {len(report['baselined'])} baselined"
             if report["baselined"] else "")
    print(f"tools.analysis: OK — {n_pass} passes, "
          f"{report['files_scanned']} files, 0 new findings{extra} "
          f"({report['seconds']:.2f}s)")
    return 0
