"""Pass modules; importing this package registers every pass with
:data:`tools.analysis.core.REGISTRY`.  Order here is execution order:
cheap regex passes first, the two AST-heavy flagship passes last."""
from tools.analysis.passes import (  # noqa: F401
    atomic_writes,
    metric_names,
    fault_sites,
    collective_instrumented,
    bounded_retries,
    excepts,
    lock_discipline,
    trace_purity,
    span_discipline,
    collective_discipline,
    sharding_spec,
)

__all__ = ["atomic_writes", "metric_names", "fault_sites",
           "collective_instrumented", "bounded_retries", "excepts",
           "lock_discipline", "trace_purity", "span_discipline",
           "collective_discipline", "sharding_spec"]
