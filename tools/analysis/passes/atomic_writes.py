"""Rule ``atomic-writes``: durable writes under paddle_tpu/ must go
through the resilience layer's tmp+rename helpers.

A file opened for write (``'w'``/``'wb'``/``'x'``/``'a'``/...)
anywhere else is a torn-file hazard: a crash mid-write corrupts
whatever used to be at that path.  ``resilience.atomic.atomic_write``
owns the tmp+``os.replace`` commit; the handful of sanctioned direct
writers (trace/log artifacts whose loss is cosmetic) carry inline
``# lint-ok: atomic-writes <reason>`` comments — the file-level
allowlist the old one-off lint kept is gone.
"""
from __future__ import annotations

import os
import re
import sys

from tools.analysis.core import (Finding, Project, apply_suppressions,
                                 register)

# open(path, "w"/"wb"/"a"/"x"/... ) with the mode as a positional or
# mode= literal; tolerates whitespace and f-string paths on one line
_OPEN_WRITE = re.compile(
    r"""\bopen\s*\(              # open(
        [^()]*?,                 #   first arg (no nested parens)
        \s*(?:mode\s*=\s*)?      #   optional mode=
        (['"])([wax]b?\+?t?)\1   #   'w' 'wb' 'a' 'ab' 'x' ...
    """, re.VERBOSE)

RULE = "atomic-writes"


@register(RULE, "durable writes go through resilience.atomic")
def find(project):
    out = []
    for mod in project.modules():
        for lineno, line in enumerate(mod.lines, 1):
            code = line.split("#", 1)[0]
            if _OPEN_WRITE.search(code):
                out.append(Finding(
                    mod.rel, lineno, RULE,
                    f"non-atomic file write: {line.strip()} — use "
                    f"paddle_tpu.resilience.atomic.atomic_write"))
    return out


# ------------------------------------------------- legacy shim surface

def check(root=None):
    """Old-format violations list: ``['paddle_tpu/<rel>:<line>: <src>']``
    (kept for the ``tools/check_atomic_writes.py`` shim)."""
    project = Project(package_root=root) if root else Project()
    by_rel = {m.rel: m for m in project.modules()}
    out = []
    for f in apply_suppressions(project, find(project)):
        mod = by_rel[f.file]
        rel = os.path.relpath(mod.path,
                              project.package_root).replace(os.sep, "/")
        out.append(f"paddle_tpu/{rel}:{f.line}: "
                   f"{mod.line_at(f.line).strip()}")
    return out


def main(argv=None):
    violations = check(argv[0] if argv else None)
    if violations:
        print("non-atomic file writes (use "
              "paddle_tpu.resilience.atomic.atomic_write):",
              file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("check_atomic_writes: OK")
    return 0
