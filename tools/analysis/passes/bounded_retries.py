"""Rule ``bounded-retries``: retry/poll loops under paddle_tpu/ must
bound themselves.

A ``while True`` that sleeps-and-retries around a network / store /
engine call turns one dead peer into a wedged process.  The contract
(``resilience/retry.py``) is that every such loop is bounded by a
:class:`Deadline` or an attempt budget — flagged here when the body
contains a *blocking edge* (``sleep``, ``recv``/``connect``/``poll``,
a ``timeout=`` call, ``next(<backoff>)``) and no bound reference.
The sanctioned unbounded daemons (supervisor child watch, dataloader
worker poll) carry ``# lint-ok: bounded-retries <reason>`` comments at
the loop header instead of the old module allowlist.
"""
from __future__ import annotations

import ast
import os
import sys

from tools.analysis.core import Finding, Project, register

_BLOCKING_NAMES = {"recv", "recv_into", "accept", "connect", "poll",
                   "serve_forever", "urlopen"}
_BOUND_IDS = {"deadline", "dl", "max_attempts", "attempt", "attempts",
              "retries"}
_BOUND_ATTRS = {"remaining", "expired"}

RULE = "bounded-retries"


def _call_name(node):
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _is_blocking(loop):
    """Does the loop body contain a blocking-edge call?"""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "sleep" or name in _BLOCKING_NAMES:
            return True
        if any(kw.arg == "timeout" for kw in node.keywords):
            return True
        if name == "next" and node.args:
            arg = node.args[0]
            arg_name = (arg.id if isinstance(arg, ast.Name) else
                        arg.attr if isinstance(arg, ast.Attribute) else "")
            if "delay" in arg_name.lower() or "backoff" in arg_name.lower():
                return True
    return False


def _is_bounded(loop):
    """Does the loop reference a Deadline / attempt budget?"""
    for node in ast.walk(loop):
        if isinstance(node, ast.Name):
            ident = node.id.lower()
            if node.id == "Deadline" or ident in _BOUND_IDS \
                    or "deadline" in ident:
                return True
        elif isinstance(node, ast.Attribute):
            attr = node.attr.lower()
            if attr in _BOUND_ATTRS or attr in _BOUND_IDS \
                    or "deadline" in attr:
                return True
    return False


def _is_forever(test):
    """``while True:`` / ``while 1:`` — a constant-true test."""
    return isinstance(test, ast.Constant) and bool(test.value)


def _find_raw(project):
    """[(Finding, fn_name)] before allowlist/suppression filtering."""
    out = []
    for mod in project.modules():
        tree = mod.tree
        if tree is None:
            continue
        # map each while-loop to its innermost enclosing function
        func_of = {}
        for fn in ast.walk(tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for node in ast.walk(fn):
                    if isinstance(node, ast.While):
                        func_of[node] = fn.name   # innermost wins (later)
        for node in ast.walk(tree):
            if not isinstance(node, ast.While) or \
                    not _is_forever(node.test):
                continue
            if not _is_blocking(node) or _is_bounded(node):
                continue
            fn_name = func_of.get(node, "<module>")
            out.append((Finding(
                mod.rel, node.lineno, RULE,
                f"unbounded 'while True' around a blocking call in "
                f"{fn_name}() — bound it with resilience.retry "
                f"(max_attempts) or a Deadline, or suppress a genuine "
                f"daemon with '# lint-ok: {RULE} <reason>'"), fn_name))
    return out


@register(RULE, "blocking retry loops carry a Deadline/attempt bound")
def find(project):
    return [f for f, _ in _find_raw(project)]


# ------------------------------------------------- legacy shim surface

#: the old module-level allowlist is empty — the sanctioned daemons now
#: carry inline ``lint-ok`` comments; kept so shim importers still find
#: the name
ALLOWLIST = set()


def check(root=None, allowlist=None):
    """Old-format list, paths relative to ``root``:
    ``['<rel>:<line> in <fn>(): unbounded ...']``."""
    project = Project(package_root=root) if root else Project()
    allow = ALLOWLIST if allowlist is None else set(allowlist)
    by_rel = {m.rel: m for m in project.modules()}
    out = []
    for f, fn_name in _find_raw(project):
        mod = by_rel[f.file]
        if mod.suppressed(RULE, f.line):
            continue
        rel = os.path.relpath(mod.path,
                              project.package_root).replace(os.sep, "/")
        if (rel, fn_name) in allow:
            continue
        out.append(
            f"{rel}:{f.line} in {fn_name}(): unbounded "
            f"'while True' around a blocking call — bound it with "
            f"resilience.retry (max_attempts) or a Deadline, or "
            f"allowlist a genuine daemon")
    return sorted(out)


def main(argv=None):
    violations = check()
    if violations:
        print("unbounded retry/poll loops (see tools/"
              "check_bounded_retries.py):", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("check_bounded_retries: OK")
    return 0
