"""Rule ``collective-discipline``: SPMD collectives must be
rank-uniform, order-stable, and deadline-bounded.

The classic multi-host failure mode is a collective some ranks never
reach: every participating rank blocks in ``all_reduce`` (or a counted
store barrier) waiting for a peer that branched the other way on
``rank == 0``.  PR 8's hang watchdog localizes that at *runtime* —
after the fleet is already wedged; this pass is its static complement,
the same way trace-purity is the static complement of the compile
watchdog.  Three finding kinds, one rule id:

- **rank-conditional hang** — a rank-uniform operation (a
  ``distributed/collective.py`` op, a ``jax.lax`` collective, a store
  ``barrier``, a ``CommitBarrier`` ``begin``/``ack``/``commit``)
  reachable on only one side of a rank-conditional branch.  Guard
  returns count: ``if rank != 0: return`` followed by ``barrier()``
  means non-zero ranks never arrive.  A *blocking store wait* on one
  side is also flagged — unless the other side *publishes* to the
  store (``set``/``add``): a one-sided wait with a matching publish is
  the sanctioned producer/consumer handshake (the begin/ack/commit
  pairing — rank 0 publishes the generation, peers block on it; rank 0
  blocks on acks that peers published), which is how
  ``distributed/checkpoint.py`` passes clean on merit.
- **order divergence** — both sides of a rank-conditional issue
  rank-uniform collectives but in *different sequences*.  Every rank
  reaches a collective, so nothing hangs immediately — ranks are
  simply executing different programs, the cross-rank desync the
  flight recorder can only name post-mortem (first divergent seq/op).
- **unbounded blocking wait** — a blocking collective-plane wait
  (store ``get``/``wait``/``barrier``) with no ``timeout=`` and no
  :class:`~paddle_tpu.resilience.retry.Deadline` in scope, or a
  ``timeout=`` that forwards an enclosing parameter whose default is
  ``None``.  Extends the bounded-retries contract to the distributed
  edge: one dead peer must cost a timeout, not a wedged fleet.

Rank predicates are recognized intraprocedurally (``rank == 0``,
``self.rank``, ``get_rank()``, ``jax.process_index()``,
``is_first``/``is_master``-style names, and locals assigned from such
expressions) plus ONE call level deep: ``if self._is_primary():``
resolves through the local def / method / ``from``-import and inspects
its returns.  Collective collection is also one call deep, so a
rank-gated helper that wraps ``all_reduce`` still counts.

Sanctioned asymmetric protocols are annotated in source with
``# rank-ok: <reason>`` on the branch (or flagged) line — recorded and
honored like ``lint-ok`` but self-documenting as a *protocol* sanction
rather than a lint waiver; ``# lint-ok: collective-discipline
<reason>`` also works.
"""
from __future__ import annotations

import ast
import re

from tools.analysis.core import Finding, register

RULE = "collective-discipline"

_RANK_OK = re.compile(r"#\s*rank-ok:\s*\S")

#: terminal call names that are rank-uniform collectives wherever they
#: appear (distinctive enough to match on any receiver)
_COLLECTIVE_NAMES = {
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "ppermute", "psum", "psum_scatter", "pmax", "pmin", "pmean",
}

#: collective.py exports that are too generic to match by name alone —
#: they count only when resolved through the collective module (a bare
#: from-import or a ``collective.`` / ``dist.`` attribute)
_GENERIC_COLLECTIVES = {"send", "recv", "scatter", "reduce", "split",
                        "broadcast", "barrier"}

#: CommitBarrier protocol methods (receiver must look barrier-like)
_BARRIER_PROTO = {"begin", "ack", "commit"}

#: blocking store waits / store publishes (receiver must look store-like)
_STORE_WAITS = {"get", "wait"}
_STORE_PUBLISHES = {"set", "add", "set_if_absent", "fadd", "mfadd",
                    "msetnx", "delete_key", "publish"}

#: rank-predicate identifiers: exact names and a containment pattern
_RANK_NAMES = {"rank", "local_rank", "global_rank", "world_rank",
               "node_rank", "process_index", "proc_index", "get_rank"}
_RANK_PATTERN = re.compile(
    r"(^|_)(rank|is_first|is_master|is_main|is_primary|is_last|"
    r"is_leader|first_worker)($|_)")


def _terminal(node):
    """Last identifier of a Name/Attribute chain, or None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_rank_name(ident):
    if ident is None:
        return False
    return ident in _RANK_NAMES or bool(_RANK_PATTERN.search(ident))


# ------------------------------------------------------------ module index


class _Index:
    """Per-module: local defs/methods, from-imports, and which local
    names denote the collective module (``import ... as dist``)."""

    def __init__(self, mod):
        self.mod = mod
        self.functions = {}      # name -> FunctionDef (module level)
        self.methods = {}        # (class, name) -> FunctionDef
        self.from_imports = {}   # local name -> (module, original)
        self.collective_aliases = set()   # names denoting collective mod
        tree = mod.tree
        if tree is None:
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                src = node.module or ""
                for a in node.names:
                    local = a.asname or a.name
                    self.from_imports[local] = (src, a.name)
                    if a.name == "collective" or \
                            src.endswith("collective"):
                        if a.name == "collective":
                            self.collective_aliases.add(local)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.endswith(".collective"):
                        self.collective_aliases.add(
                            a.asname or a.name.split(".")[0])
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
        for cls in ast.walk(tree):
            if isinstance(cls, ast.ClassDef):
                for node in cls.body:
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.methods[(cls.name, node.name)] = node

    def imports_collective_name(self, name):
        """Is ``name`` a bare from-import out of the collective module?"""
        src = self.from_imports.get(name)
        return bool(src and (src[0].endswith("collective")
                             or src[0].endswith("distributed")))


class _Universe:
    """Cross-module resolution: one level deep, by simple name."""

    def __init__(self, project):
        self.indexes = {}
        for mod in project.modules():
            if mod.tree is not None:
                self.indexes[mod.rel] = _Index(mod)

    def resolve_import(self, index, name):
        """FunctionDef a from-import lands on in another module."""
        src = index.from_imports.get(name)
        if not src:
            return None, None
        module, orig = src
        for rel, idx in self.indexes.items():
            modname = rel[:-3].replace("/", ".")
            if module and (modname == module
                           or modname.endswith("." + module.lstrip("."))
                           or modname.endswith(module.lstrip("."))):
                fn = idx.functions.get(orig)
                if fn is not None:
                    return fn, idx
        return None, None

    def resolve_call(self, call, index, cls_name):
        """(FunctionDef, owning _Index) for a call, one level deep."""
        fn = call.func
        if isinstance(fn, ast.Name):
            target = index.functions.get(fn.id)
            if target is not None:
                return target, index
            return self.resolve_import(index, fn.id)
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                and cls_name is not None:
            target = index.methods.get((cls_name, fn.attr))
            if target is not None:
                return target, index
        return None, None


# ------------------------------------------------------ rank predicates


def _expr_mentions_rank(node, rank_locals):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            if sub.id in rank_locals or _is_rank_name(sub.id):
                return True
        elif isinstance(sub, ast.Attribute):
            if _is_rank_name(sub.attr):
                return True
        elif isinstance(sub, ast.Call):
            if _is_rank_name(_terminal(sub.func)):
                return True
    return False


def _returns_rank_predicate(fn):
    """One-call-deep predicate resolution: does ``fn``'s return
    expression read a rank?"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            if _expr_mentions_rank(node.value, frozenset()):
                return True
    return False


def _is_rank_conditional(test, rank_locals, universe, index, cls_name):
    """Is this ``if`` test a rank predicate (direct, via a tainted
    local, or through one resolvable call)?"""
    if _expr_mentions_rank(test, rank_locals):
        return True
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call):
            target, _ = universe.resolve_call(sub, index, cls_name)
            if target is not None and _returns_rank_predicate(target):
                return True
    return False


def _rank_tainted_locals(fn, universe, index, cls_name):
    """Locals assigned from rank expressions (``am_zero = rank == 0``,
    ``primary = self._is_primary()``)."""
    tainted = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            value = node.value
            hit = _expr_mentions_rank(value, tainted)
            if not hit and isinstance(value, ast.Call):
                target, _ = universe.resolve_call(value, index, cls_name)
                hit = target is not None and \
                    _returns_rank_predicate(target)
            if hit:
                tainted.add(node.targets[0].id)
    return tainted


# ------------------------------------------------------- event collection


class _Event:
    """One collective-plane operation: kind is 'uniform', 'wait' or
    'publish'; ``op`` names it for order comparison."""

    __slots__ = ("kind", "op", "lineno")

    def __init__(self, kind, op, lineno):
        self.kind = kind
        self.op = op
        self.lineno = lineno


def _local_aliases(fn):
    """name -> unparsed source for simple local assignments; lets
    ``b = self._barrier`` / ``s = self._stores[0]`` keep their flavor."""
    out = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            try:
                out[node.targets[0].id] = ast.unparse(node.value).lower()
            except Exception:   # pragma: no cover - malformed nodes
                pass
    return out


#: word/camel-hump-start match so 'restored'/'restore_fit_state' do
#: not read as stores while 'self.store', '_store', 'TCPStore',
#: 'stores[0]' all do
_STOREISH = re.compile(r"(?<![a-z])[Ss]tore")
_BARRIERISH = re.compile(r"(?<![a-z])[Bb]arrier")


def _receiver_flavor(call, aliases, cls_name=None):
    """'store' / 'barrier' / '' for an attribute call's receiver."""
    if not isinstance(call.func, ast.Attribute):
        return ""
    src = _dotted(call.func.value) or ""
    head = src.split(".")[0] if src else ""
    if src == "self" and cls_name:
        # a method on the store/barrier class itself: self IS one
        src = cls_name
    elif head in aliases:
        src = src + " " + aliases[head]
    if _STOREISH.search(src):
        return "store"
    if _BARRIERISH.search(src):
        return "barrier"
    return ""


def _is_blocking_wait(call):
    """A store get/wait is blocking unless ``blocking=False``."""
    for kw in call.keywords:
        if kw.arg == "blocking" and \
                isinstance(kw.value, ast.Constant) and \
                kw.value.value is False:
            return False
    return True


def _classify_call(call, index, aliases, cls_name=None):
    """The _Event a Call contributes, or None."""
    name = _terminal(call.func)
    if name is None:
        return None
    flavor = _receiver_flavor(call, aliases, cls_name)
    if name in _COLLECTIVE_NAMES:
        return _Event("uniform", name, call.lineno)
    if name in _GENERIC_COLLECTIVES:
        if isinstance(call.func, ast.Name):
            if index.imports_collective_name(name):
                return _Event("uniform", name, call.lineno)
        elif isinstance(call.func, ast.Attribute):
            head = _dotted(call.func.value) or ""
            if head.split(".")[0] in index.collective_aliases or \
                    head.endswith("collective"):
                return _Event("uniform", name, call.lineno)
            if name == "barrier" and flavor in ("store", "barrier"):
                return _Event("uniform", "store.barrier", call.lineno)
        return None
    if name in _BARRIER_PROTO and flavor == "barrier":
        return _Event("uniform", f"barrier.{name}", call.lineno)
    if name in _STORE_WAITS and flavor == "store":
        if _is_blocking_wait(call):
            return _Event("wait", f"store.{name}", call.lineno)
        return None
    if name in _STORE_PUBLISHES and flavor in ("store", "barrier"):
        return _Event("publish", f"store.{name}", call.lineno)
    return None


def _collect_events(stmts, index, aliases, universe, cls_name,
                    depth=1, fn_seen=None):
    """Ordered collective-plane events in a statement list, descending
    into resolvable calls ``depth`` more levels."""
    events = []
    fn_seen = fn_seen or set()
    for stmt in stmts:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            ev = _classify_call(node, index, aliases, cls_name)
            if ev is not None:
                events.append(ev)
                continue
            if depth > 0:
                target, tidx = universe.resolve_call(node, index,
                                                     cls_name)
                if target is not None and id(target) not in fn_seen:
                    fn_seen = fn_seen | {id(target)}
                    sub_aliases = _local_aliases(target)
                    sub = _collect_events(
                        target.body, tidx, sub_aliases, universe,
                        cls_name, depth=depth - 1, fn_seen=fn_seen)
                    for s in sub:
                        events.append(_Event(s.kind, s.op, node.lineno))
    return events


def _terminates(stmts):
    """Does this branch end control flow (return/raise/continue/break
    as its final statement)?"""
    if not stmts:
        return False
    last = stmts[-1]
    return isinstance(last, (ast.Return, ast.Raise, ast.Continue,
                             ast.Break))


# ------------------------------------------------------------- the walk


def _rank_ok(mod, lineno):
    """``# rank-ok: <reason>`` on the line or the comment block above."""
    if _RANK_OK.search(mod.line_at(lineno)):
        return True
    ln = lineno - 1
    while ln >= 1 and mod.line_at(ln).strip().startswith("#"):
        if _RANK_OK.search(mod.line_at(ln)):
            return True
        ln -= 1
    return False


def _seq_str(events):
    return " -> ".join(e.op for e in events) or "(none)"


def _check_branches(mod, fn, if_node, body_ev, else_ev, else_label,
                    findings):
    """Compare the two sides of one rank-conditional."""
    if _rank_ok(mod, if_node.lineno):
        return
    b_uniform = [e for e in body_ev if e.kind == "uniform"]
    e_uniform = [e for e in else_ev if e.kind == "uniform"]
    b_ops = [e.op for e in b_uniform]
    e_ops = [e.op for e in e_uniform]
    if b_ops != e_ops:
        if b_ops and e_ops:
            findings.append(Finding(
                mod.rel, if_node.lineno, RULE,
                f"order divergence in {fn.name}(): branches of a "
                f"rank-conditional issue different collective "
                f"sequences [{_seq_str(b_uniform)}] vs "
                f"[{_seq_str(e_uniform)}] ({else_label}) — ranks will "
                f"execute different programs; make the sequences "
                f"identical or annotate the protocol with "
                f"'# rank-ok: <reason>'"))
        else:
            one = b_uniform or e_uniform
            where = "only one side" if if_node.orelse or not b_uniform \
                else "only the rank-conditional branch"
            findings.append(Finding(
                mod.rel, one[0].lineno, RULE,
                f"rank-conditional hang in {fn.name}(): collective "
                f"'{one[0].op}' is reachable on {where} of a "
                f"rank-conditional ({else_label}) — ranks on the "
                f"other side never arrive and the fleet blocks; hoist "
                f"the collective out of the branch or annotate the "
                f"protocol with '# rank-ok: <reason>'"))
        return
    # uniform sequences agree; check one-sided blocking waits with no
    # matching publish on the opposite side (the sanctioned handshake:
    # one side waits on what the other side publishes)
    b_wait = [e for e in body_ev if e.kind == "wait"]
    e_wait = [e for e in else_ev if e.kind == "wait"]
    b_pub = any(e.kind == "publish" for e in body_ev)
    e_pub = any(e.kind == "publish" for e in else_ev)
    for waits, other_pub in ((b_wait, e_pub), (e_wait, b_pub)):
        if waits and not other_pub:
            w = waits[0]
            if _rank_ok(mod, w.lineno):
                continue
            findings.append(Finding(
                mod.rel, w.lineno, RULE,
                f"one-sided blocking wait in {fn.name}(): "
                f"'{w.op}' blocks under a rank-conditional with no "
                f"matching publish on the other side — if the "
                f"producer rank took the other branch, nothing ever "
                f"lands and this rank hangs until timeout; pair the "
                f"wait with a publish or annotate with "
                f"'# rank-ok: <reason>'"))


class _FnWalker:
    """Walk one function finding rank-conditionals and comparing the
    collective-plane event sequences of their sides."""

    def __init__(self, mod, fn, cls_name, universe, index):
        self.mod = mod
        self.fn = fn
        self.cls_name = cls_name
        self.universe = universe
        self.index = index
        self.aliases = _local_aliases(fn)
        self.rank_locals = _rank_tainted_locals(fn, universe, index,
                                                cls_name)
        self.findings = []

    def _events(self, stmts):
        return _collect_events(stmts, self.index, self.aliases,
                               self.universe, self.cls_name)

    def run(self):
        self._walk(self.fn.body)
        return self.findings

    def _walk(self, stmts):
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, ast.If) and _is_rank_conditional(
                    stmt.test, self.rank_locals, self.universe,
                    self.index, self.cls_name):
                body_ev = self._events(stmt.body)
                if stmt.orelse:
                    else_ev = self._events(stmt.orelse)
                    label = "if/else"
                elif _terminates(stmt.body):
                    # guard-return: the other side is the fallthrough
                    else_ev = self._events(stmts[i + 1:])
                    label = "guard return vs fallthrough"
                else:
                    # no else and no early exit: the other side is
                    # empty — the branch body alone is the divergence
                    else_ev = []
                    label = "no else branch"
                _check_branches(self.mod, self.fn, stmt, body_ev,
                                else_ev, label, self.findings)
                # still recurse for nested rank-conditionals
                self._walk(stmt.body)
                self._walk(stmt.orelse)
                continue
            for block in _sub_blocks(stmt):
                self._walk(block)


def _sub_blocks(stmt):
    for field in ("body", "orelse", "finalbody"):
        block = getattr(stmt, field, None)
        if isinstance(block, list):
            yield block
    for handler in getattr(stmt, "handlers", ()) or ():
        yield handler.body


# --------------------------------------------------- unbounded-wait check


def _param_defaults_none(fn):
    """Parameter names whose default is literally None."""
    args = fn.args
    out = set()
    pos = args.posonlyargs + args.args
    for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        if isinstance(d, ast.Constant) and d.value is None:
            out.add(a.arg)
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None and isinstance(d, ast.Constant) and \
                d.value is None:
            out.add(a.arg)
    return out


def _mentions_deadline(fn):
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and \
                "deadline" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) and (
                "deadline" in node.attr.lower()
                or node.attr in ("remaining", "expired")):
            return True
    return False


def _check_unbounded_waits(mod, fn, index, universe, cls_name, findings):
    aliases = _local_aliases(fn)
    none_params = _param_defaults_none(fn)
    has_deadline = _mentions_deadline(fn)
    reassigned = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    reassigned.add(tgt.id)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal(node.func)
        flavor = _receiver_flavor(node, aliases, cls_name)
        is_wait = (name in _STORE_WAITS and flavor == "store"
                   and _is_blocking_wait(node)) or \
            (name == "barrier" and flavor in ("store", "barrier")
             and isinstance(node.func, ast.Attribute))
        if not is_wait:
            continue
        timeout_kw = next((kw for kw in node.keywords
                           if kw.arg == "timeout"), None)
        if timeout_kw is None:
            if has_deadline:
                continue
            findings.append(Finding(
                mod.rel, node.lineno, RULE,
                f"unbounded blocking wait in {fn.name}(): "
                f"'{name}(...)' has no timeout= and no Deadline in "
                f"scope — a dead peer wedges this rank forever; pass "
                f"timeout= or bound the enclosing loop with a "
                f"Deadline"))
            continue
        v = timeout_kw.value
        if isinstance(v, ast.Constant) and v.value is None:
            if not has_deadline:
                findings.append(Finding(
                    mod.rel, node.lineno, RULE,
                    f"unbounded blocking wait in {fn.name}(): "
                    f"'{name}(timeout=None)' with no Deadline in "
                    f"scope — pass a real bound"))
            continue
        if isinstance(v, ast.Name) and v.id in none_params and \
                v.id not in reassigned and not has_deadline:
            findings.append(Finding(
                mod.rel, node.lineno, RULE,
                f"unbounded blocking wait in {fn.name}(): "
                f"'{name}(timeout={v.id})' forwards a parameter that "
                f"defaults to None with no Deadline in scope — the "
                f"default path has no total bound; derive the "
                f"timeout from a Deadline or a non-None default"))


# ---------------------------------------------------------------- driver


def _functions_of(tree):
    out = []

    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                out.append((cls, child))
                walk(child, cls)
            else:
                walk(child, cls)

    walk(tree, None)
    return out


#: identifiers whose presence in a function makes the wait check
#: worth running at all
_WAITISH = _STORE_WAITS | {"barrier"}

#: any collective-plane identifier: a function containing one must run
#: the branch walker even without a literal rank name in scope — the
#: rank predicate may be a resolvable call ('if should_lead():')
_OPISH = (_COLLECTIVE_NAMES | _GENERIC_COLLECTIVES | _BARRIER_PROTO
          | _STORE_WAITS | _STORE_PUBLISHES)


def _fn_idents(fn):
    """Every Name/Attribute identifier in one function — the one-walk
    gate that lets the expensive analyses skip the vast majority of
    functions (no rank-y name => no rank conditional is expressible;
    no get/wait/barrier => no blocking wait to bound)."""
    idents = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            idents.add(node.id)
        elif isinstance(node, ast.Attribute):
            idents.add(node.attr)
    return idents


@register(RULE, "collectives rank-uniform, order-stable, deadline-bounded")
def find(project):
    universe = _Universe(project)
    findings = []
    for mod in project.scoped_modules():
        tree = mod.tree
        if tree is None:
            continue
        index = universe.indexes.get(mod.rel)
        if index is None:
            continue
        for cls_name, fn in _functions_of(tree):
            idents = _fn_idents(fn)
            has_rank = any(_is_rank_name(i) for i in idents)
            if has_rank or idents & _OPISH:
                walker = _FnWalker(mod, fn, cls_name, universe, index)
                for f in walker.run():
                    if not _rank_ok(mod, f.line):
                        findings.append(f)
            if idents & _WAITISH:
                _check_unbounded_waits(mod, fn, index, universe,
                                       cls_name, findings)
    findings.sort(key=lambda f: (f.file, f.line))
    return findings


def collective_sites(project):
    """Every recognized collective-plane call site
    ``[(rel, lineno, kind, op)]`` — bench/tests introspect coverage."""
    universe = _Universe(project)
    out = []
    for mod in project.modules():
        tree = mod.tree
        if tree is None:
            continue
        index = universe.indexes.get(mod.rel)
        for cls_name, fn in _functions_of(tree):
            aliases = _local_aliases(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    ev = _classify_call(node, index, aliases, cls_name)
                    if ev is not None:
                        out.append((mod.rel, ev.lineno, ev.kind, ev.op))
    return sorted(set(out))
