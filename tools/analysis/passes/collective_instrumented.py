"""Rule ``collective-instrumented``: every public op in
``distributed/collective.py`` must route through the distributed
flight recorder.

Reads the module's ``__all__`` literal and requires each exported
module-level function (group factories ``new_group``/``get_group``
exempt, classes skipped naturally) to carry the
``@record_collective("<op>")`` decorator from
:mod:`paddle_tpu.observability.flight`.
"""
from __future__ import annotations

import ast
import sys

from tools.analysis.core import (Finding, Project, SourceModule,
                                 apply_suppressions, register)

#: exported names that are op *plumbing*, not collectives
EXEMPT = {"new_group", "get_group"}

RULE = "collective-instrumented"


def _exported_names(tree):
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                return {elt.value for elt in node.value.elts
                        if isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)}
    return set()


def _decorator_name(dec):
    f = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _instrumented(fn):
    return any(_decorator_name(d) == "record_collective"
               for d in fn.decorator_list)


def _find_in_module(mod):
    tree = mod.tree
    if tree is None:
        return []
    exported = _exported_names(tree)
    out = []
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name not in exported or node.name in EXEMPT:
            continue
        if not _instrumented(node):
            out.append(Finding(
                mod.rel, node.lineno, RULE,
                f"public collective op {node.name!r} not routed "
                f"through the flight recorder — add "
                f'@record_collective("{node.name}")'))
    return out


@register(RULE, "every public collective op flight-recorded")
def find(project):
    mod = project.module("distributed/collective.py")
    return _find_in_module(mod) if mod is not None else []


# ------------------------------------------------- legacy shim surface

def check(path=None):
    """Old-format list ``['op (path:line): problem']``."""
    if path is None:
        project = Project()
        findings = apply_suppressions(project, find(project))
    else:
        mod = SourceModule(path, path.rsplit("/", 1)[-1])
        findings = [f for f in _find_in_module(mod)
                    if not mod.suppressed(RULE, f.line)]
    out = []
    for f in findings:
        op = f.message.split("'")[1]
        out.append(f"{op} ({f.file}:{f.line}): public collective op "
                   f"not routed through the flight recorder — add "
                   f'@record_collective("{op}")')
    return out


def main(argv=None):
    uncovered = check(argv[0] if argv else None)
    if uncovered:
        print("silently untraced collectives "
              "(see tools/check_collective_instrumented.py):",
              file=sys.stderr)
        for u in uncovered:
            print(f"  {u}", file=sys.stderr)
        return 1
    print("check_collective_instrumented: OK")
    return 0
