"""Rule ``excepts``: no silent broad-exception swallows under
paddle_tpu/.

Flags any handler that catches **broadly** (bare ``except:``,
``Exception`` or ``BaseException``, alone or in a tuple) and **does
nothing** (only ``pass``/``continue``/``break``/constants).  A flagged
handler must log, re-raise, recover with real code, narrow its
exception list, or carry an explicit reason: either the uniform
``# lint-ok: excepts <reason>`` on the ``except`` line, or the
rule-native ``# silent-ok: <reason>`` anywhere on the handler's source
lines (the form seeded across the package's genuine cleanup paths).
The reason is mandatory in both spellings.
"""
from __future__ import annotations

import ast
import re
import sys

from tools.analysis.core import (Finding, Project, apply_suppressions,
                                 register)

# the reason is mandatory in both spellings: a naked marker is still
# a violation
MARKER = re.compile(r"#\s*(?:silent-ok:|lint-ok:\s*excepts\s)\s*\S")

_BROAD = ("Exception", "BaseException")

RULE = "excepts"


def _catches_broadly(handler):
    t = handler.type
    if t is None:                           # bare except:
        return True

    def name_of(node):
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    if isinstance(t, ast.Tuple):
        return any(name_of(e) in _BROAD for e in t.elts)
    return name_of(t) in _BROAD


def _does_nothing(handler):
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue                        # docstring / ellipsis
        return False
    return True


def _allowlisted(handler, lines):
    last = max(getattr(s, "end_lineno", s.lineno) for s in handler.body)
    blob = "\n".join(lines[handler.lineno - 1:last])
    return bool(MARKER.search(blob))


@register(RULE, "no silent broad-exception swallows")
def find(project):
    out = []
    for mod in project.modules():
        tree = mod.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not (_catches_broadly(node) and _does_nothing(node)):
                continue
            if _allowlisted(node, mod.lines):
                continue
            what = ("bare except" if node.type is None
                    else f"except {ast.unparse(node.type)}")
            out.append(Finding(
                mod.rel, node.lineno, RULE,
                f"{what} swallows silently — log, re-raise, narrow "
                f"the exception, or add '# silent-ok: <reason>'"))
    return out


# ------------------------------------------------- legacy shim surface

def check(root=None):
    """Old-format list ``['relpath:lineno: except <what>']``."""
    project = Project(package_root=root) if root else Project()
    out = []
    for f in apply_suppressions(project, find(project)):
        what = f.message.split(" swallows", 1)[0]
        out.append(f"{f.file}:{f.line}: {what}")
    return sorted(out)


def main(argv=None):
    bad = check()
    if bad:
        print("silent broad-exception swallows (log, re-raise, narrow "
              "the exception, or add '# silent-ok: <reason>'):",
              file=sys.stderr)
        for b in bad:
            print(f"  {b}", file=sys.stderr)
        return 1
    print("check_excepts: OK (no silent broad swallows)")
    return 0
