"""Rule ``fault-sites``: every fault site registered under paddle_tpu/
must be exercised by at least one test — and every fault *kind* too.

Collects every site name declared in the package (positional
``fault_point("...")`` literals and ``site="..."`` keyword literals)
and checks that each name appears somewhere under tests/.  Keyword
*defaults* (like ``atomic_write``'s ``site="io.write"``) declare a
parameter, not a site, and are skipped.

Kinds ride the same rule: the ``FAULT_KINDS`` tuple assignment in
``resilience/faults.py`` is read by AST (kill / torn_write / io_error /
stall / bitflip / poison_request, plus whatever a later PR adds) and
each kind must appear in the tests blob — a fault kind nobody can
inject in a test is dead chaos surface.
"""
from __future__ import annotations

import ast
import sys

from tools.analysis.core import (Finding, Project, apply_suppressions,
                                 register)

RULE = "fault-sites"


def _collect(project):
    """``{site_name: (mod, lineno)}`` for every literal fault site."""
    sites = {}
    for mod in project.modules():
        tree = mod.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fn_name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if fn_name == "fault_point" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                sites.setdefault(node.args[0].value, (mod, node.lineno))
            for kw in node.keywords:
                if kw.arg == "site" and \
                        isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, str):
                    sites.setdefault(kw.value.value, (mod, node.lineno))
    return sites


def _collect_kinds(project):
    """``{kind: (mod, lineno)}`` from the ``FAULT_KINDS = (...)``
    tuple assignment in ``resilience/faults.py`` (AST, not import)."""
    kinds = {}
    for mod in project.modules():
        if not mod.rel.endswith("resilience/faults.py"):
            continue
        tree = mod.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == "FAULT_KINDS"
                       for t in node.targets):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        kinds.setdefault(elt.value, (mod, elt.lineno))
    return kinds


@register(RULE, "every fault site and fault kind exercised by a test")
def find(project):
    sites = _collect(project)
    blob = project.tests_blob()
    out = []
    for name, (mod, lineno) in sorted(sites.items()):
        if name not in blob:
            out.append(Finding(
                mod.rel, lineno, RULE,
                f"fault site {name!r} has no exercising test — add a "
                f"matrix case (e.g. injected_faults(FaultSpec"
                f"({name!r}, ...)))"))
    for kind, (mod, lineno) in sorted(_collect_kinds(project).items()):
        if kind not in blob:
            out.append(Finding(
                mod.rel, lineno, RULE,
                f"fault kind {kind!r} has no exercising test — inject "
                f"it somewhere (FaultSpec(<site>, {kind!r}, ...))"))
    return out


# ------------------------------------------------- legacy shim surface

def collect_sites(root=None):
    """``{site_name: 'relpath:lineno'}`` — old shim surface."""
    project = Project(package_root=root) if root else Project()
    return {name: f"{mod.rel}:{lineno}"
            for name, (mod, lineno) in _collect(project).items()}


def covered_sites(sites, tests_root=None):
    """The subset of ``sites`` whose name appears in any test file."""
    project = Project(tests_root=tests_root) if tests_root else Project()
    blob = project.tests_blob()
    return {s for s in sites if s in blob}


def check(root=None, tests_root=None):
    """Old-format list ``['site (declared at path:line)']``."""
    project = Project(package_root=root, tests_root=tests_root)
    return [f"{_site_of(f.message)} (declared at {f.file}:{f.line})"
            for f in apply_suppressions(project, find(project))]


def _site_of(message):
    # message leads with "fault site '<name>' has no ..."
    return message.split("'")[1]


def main(argv=None):
    uncovered = check()
    if uncovered:
        print("fault sites with no exercising test (add a matrix case "
              "in tests/, e.g. injected_faults(FaultSpec(site, ...))):",
              file=sys.stderr)
        for u in uncovered:
            print(f"  {u}", file=sys.stderr)
        return 1
    print(f"check_fault_sites: OK ({len(collect_sites())} sites covered)")
    return 0
