"""Rule ``lock-discipline``: statically check the repo's threading
conventions against declared lock ownership.

Seventeen modules guard shared state by convention only.  This pass
makes the convention machine-checked via source annotations::

    self._ring = []          # guarded-by: self._lock
    _REGISTRY = {}           # guarded-by: _LOCK     (module global)

Three finding kinds, all under one rule id:

- **unguarded access** — a read or write of a ``guarded-by`` attribute
  outside a region holding its declared lock.  Regions are tracked
  intraprocedurally through the AST: ``with self._lock:`` bodies
  (including multi-item and aliased ``with``), and explicit
  ``lock.acquire()`` … ``lock.release()`` spans (the
  ``try``/``finally`` idiom).
- **lock-order cycle** — nested lock regions contribute edges to a
  global (cross-module) acquisition-order graph; any cycle is a
  deadlock hazard.
- **split check-then-act** — within one function, an attribute *read
  in a test position* (an ``if``/``while``/``assert`` condition, a
  comparison or boolean expression) inside one lock region and
  *mutated* in a LATER, separate region of the same lock: the check's
  answer may be stale by the time the mutation runs.

Conventions the pass understands:

- ``__init__`` / ``__del__`` bodies are exempt — the object is not
  yet (no longer) shared.
- a method whose name ends in ``_locked`` asserts "caller holds the
  lock(s)": its body is analyzed with every declared lock held.  The
  pass cannot verify the *callers* (intraprocedural); the suffix is
  the documented contract.
- mutating method calls (``.append``/``.pop``/``.update``/...) and
  subscript stores on a guarded attribute count as writes.
- false positives are silenced per line with
  ``# lint-ok: lock-discipline <reason>``.

Known limits (documented, not fixed): the analysis is per-function, so
a helper called with the lock held must use the ``_locked`` suffix;
lock identity is the *declared expression* qualified by module+class,
so two classes aliasing one lock object are distinct graph nodes.
"""
from __future__ import annotations

import ast
import re

from tools.analysis.core import Finding, register

RULE = "lock-discipline"

_GUARD = re.compile(
    r"#[^#]*?\bguarded-by:\s*(?P<lock>[A-Za-z_][A-Za-z0-9_.]*)")

#: method names on a guarded container that mutate it
MUTATORS = {"append", "appendleft", "extend", "insert", "remove", "pop",
            "popleft", "popitem", "clear", "update", "setdefault", "add",
            "discard", "sort", "reverse", "write"}

#: methods whose body runs before/after the object is shared
_EXEMPT_METHODS = {"__init__", "__del__"}


def _unparse(node):
    try:
        return ast.unparse(node)
    except Exception:   # pragma: no cover - malformed fixture nodes
        return ""


def _line_guard(mod, lineno):
    m = _GUARD.search(mod.line_at(lineno))
    return m.group("lock") if m else None


class _Region:
    """One contiguous hold of one lock inside one function."""

    __slots__ = ("lock", "lineno", "reads", "writes", "checked")

    def __init__(self, lock, lineno):
        self.lock = lock
        self.lineno = lineno
        self.reads = {}      # attr -> first lineno
        self.writes = {}
        self.checked = {}    # attr read in a test position -> lineno


def collect_guards(mod):
    """``(class_guards, global_guards, lock_names)`` for one module.

    ``class_guards``: {class_name: {attr: lock_expr}} from annotated
    ``self.X = ...`` lines; ``global_guards``: {name: lock_expr} from
    annotated module-level assignments.  ``lock_names``: every lock
    expression declared anywhere in the module (with its qualified id).
    """
    tree = mod.tree
    class_guards, global_guards = {}, {}
    if tree is None:
        return class_guards, global_guards

    def targets_of(node):
        if isinstance(node, ast.Assign):
            return node.targets
        if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            return [node.target]
        return []

    for node in tree.body:
        lock = None
        for tgt in targets_of(node):
            if isinstance(tgt, ast.Name):
                lock = lock or _line_guard(mod, node.lineno)
                if lock:
                    global_guards[tgt.id] = lock

    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guards = {}
        for node in ast.walk(cls):
            for tgt in targets_of(node):
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    lock = _line_guard(mod, node.lineno)
                    if lock:
                        guards[tgt.attr] = lock
        if guards:
            class_guards[cls.name] = guards
    return class_guards, global_guards


class _FunctionAnalyzer:
    """Walk one function's statements tracking held locks."""

    def __init__(self, mod, fn, attr_guards, name_guards, qualify,
                 assume_all_held=False, decl_lines=None):
        self.mod = mod
        self.fn = fn
        self.attr_guards = attr_guards      # {attr: lock_expr}
        self.name_guards = name_guards      # {global/local: lock_expr}
        self.decl_lines = decl_lines or {}  # name -> its annotated line
        self.qualify = qualify              # lock_expr -> qualified id
        self.lock_exprs = set(attr_guards.values()) | \
            set(name_guards.values())
        self.held = {}                      # lock_expr -> depth
        self.order_stack = []               # acquisition order
        self.active = {}                    # lock_expr -> _Region
        self.regions = {}                   # lock_expr -> [_Region]
        self.findings = []
        self.edges = []                     # (qual_a, qual_b, lineno)
        if assume_all_held:
            for lk in self.lock_exprs:
                self.held[lk] = 1
                self.active[lk] = _Region(lk, fn.lineno)

    # ---- lock bookkeeping ------------------------------------------------
    def _enter(self, lock, lineno):
        self.held[lock] = self.held.get(lock, 0) + 1
        if self.held[lock] == 1:
            region = _Region(lock, lineno)
            self.active[lock] = region
            self.regions.setdefault(lock, []).append(region)
            for prior in self.order_stack:
                if prior != lock:
                    self.edges.append((self.qualify(prior),
                                       self.qualify(lock), lineno))
            self.order_stack.append(lock)

    def _exit(self, lock):
        depth = self.held.get(lock, 0)
        if depth <= 1:
            self.held.pop(lock, None)
            self.active.pop(lock, None)
            if lock in self.order_stack:
                self.order_stack.remove(lock)
        else:
            self.held[lock] = depth - 1

    def _is_lock_expr(self, node):
        src = _unparse(node)
        return src if src in self.lock_exprs else None

    # ---- access recording ------------------------------------------------
    def _record(self, kind, attr, lock, lineno, store, in_test):
        if self.decl_lines.get(attr) == lineno:
            return      # the annotated declaration itself (unshared yet)
        if self.held.get(lock, 0):
            region = self.active.get(lock)
            if region is not None:
                (region.writes if store else region.reads).setdefault(
                    attr, lineno)
                if in_test and not store:
                    region.checked.setdefault(attr, lineno)
            return
        what = "write to" if store else "read of"
        self.findings.append(Finding(
            self.mod.rel, lineno, RULE,
            f"unguarded {what} {kind} '{attr}' (guarded-by {lock}) in "
            f"{self.fn.name}() — hold {lock} or suppress with "
            f"'# lint-ok: {RULE} <reason>'"))

    # ---- expression scan -------------------------------------------------
    def scan_expr(self, node, store=False, in_test=False):
        if node is None:
            return
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and \
                    node.attr in self.attr_guards:
                self._record("attribute", f"self.{node.attr}",
                             self.attr_guards[node.attr], node.lineno,
                             store, in_test)
            self.scan_expr(node.value, store=False, in_test=in_test)
            return
        if isinstance(node, ast.Name):
            if node.id in self.name_guards:
                self._record("global", node.id,
                             self.name_guards[node.id], node.lineno,
                             store, in_test)
            return
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute):
                mutates = fn.attr in MUTATORS
                self.scan_expr(fn.value, store=mutates, in_test=in_test)
            else:
                self.scan_expr(fn, in_test=in_test)
            for a in node.args:
                self.scan_expr(a, in_test=in_test)
            for kw in node.keywords:
                self.scan_expr(kw.value, in_test=in_test)
            return
        if isinstance(node, ast.Subscript):
            target_store = store or isinstance(node.ctx,
                                               (ast.Store, ast.Del))
            self.scan_expr(node.value, store=target_store,
                           in_test=in_test)
            self.scan_expr(node.slice, in_test=in_test)
            return
        if isinstance(node, (ast.Compare, ast.BoolOp)):
            for child in ast.iter_child_nodes(node):
                self.scan_expr(child, in_test=True)
            return
        if isinstance(node, ast.IfExp):
            self.scan_expr(node.test, in_test=True)
            self.scan_expr(node.body, in_test=in_test)
            self.scan_expr(node.orelse, in_test=in_test)
            return
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return      # separate scope: analyzed as its own function
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword,
                                  ast.comprehension)):
                self.scan_expr(child, store=False, in_test=in_test)
            elif isinstance(child, ast.expr_context) or \
                    isinstance(child, (ast.operator, ast.cmpop,
                                       ast.boolop, ast.unaryop)):
                continue
            else:
                self.scan_expr(child, store=False, in_test=in_test)

    # ---- statement walk --------------------------------------------------
    def run(self):
        self.visit_block(self.fn.body)
        return self

    def visit_block(self, stmts):
        acquired_here = []
        for stmt in stmts:
            acquired_here.extend(self.visit_stmt(stmt))
        # a lock .acquire()d in this block and never .release()d stays
        # held only within the block (e.g. acquire + try/finally whose
        # finally released it already popped it)
        for lock in acquired_here:
            if self.held.get(lock, 0):
                self._exit(lock)

    def visit_stmt(self, stmt):
        """Returns locks acquire()d by this statement (still held)."""
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered = []
            for item in stmt.items:
                lock = self._is_lock_expr(item.context_expr)
                if lock is not None:
                    self._enter(lock, stmt.lineno)
                    entered.append(lock)
                else:
                    self.scan_expr(item.context_expr)
                if item.optional_vars is not None:
                    self.scan_expr(item.optional_vars, store=True)
            self.visit_block(stmt.body)
            for lock in reversed(entered):
                self._exit(lock)
            return []
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute):
                lock = self._is_lock_expr(call.func.value)
                if lock is not None and call.func.attr == "acquire":
                    self._enter(lock, stmt.lineno)
                    return [lock]
                if lock is not None and call.func.attr == "release":
                    self._exit(lock)
                    return []
            self.scan_expr(stmt.value)
            return []
        if isinstance(stmt, ast.If):
            self.scan_expr(stmt.test, in_test=True)
            self.visit_block(stmt.body)
            self.visit_block(stmt.orelse)
            return []
        if isinstance(stmt, ast.While):
            self.scan_expr(stmt.test, in_test=True)
            self.visit_block(stmt.body)
            self.visit_block(stmt.orelse)
            return []
        if isinstance(stmt, ast.For) or isinstance(stmt, ast.AsyncFor):
            self.scan_expr(stmt.target, store=True)
            self.scan_expr(stmt.iter)
            self.visit_block(stmt.body)
            self.visit_block(stmt.orelse)
            return []
        if isinstance(stmt, ast.Try):
            self.visit_block(stmt.body)
            for handler in stmt.handlers:
                self.visit_block(handler.body)
            self.visit_block(stmt.orelse)
            self.visit_block(stmt.finalbody)
            return []
        if isinstance(stmt, ast.Assert):
            self.scan_expr(stmt.test, in_test=True)
            self.scan_expr(stmt.msg)
            return []
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                self.scan_expr(tgt, store=True)
            self.scan_expr(stmt.value)
            return []
        if isinstance(stmt, ast.AugAssign):
            # read-modify-write: both an access and a mutation
            self.scan_expr(stmt.target, store=True)
            self.scan_expr(stmt.value)
            return []
        if isinstance(stmt, ast.AnnAssign):
            self.scan_expr(stmt.target, store=True)
            self.scan_expr(stmt.value)
            return []
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                self.scan_expr(tgt, store=True)
            return []
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return []   # nested scope: analyzed separately
        if isinstance(stmt, (ast.Return, ast.Expr, ast.Raise)):
            for child in ast.iter_child_nodes(stmt):
                self.scan_expr(child)
            return []
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self.visit_stmt(child)
            else:
                self.scan_expr(child)
        return []

    # ---- post-pass: split check-then-act ---------------------------------
    def check_then_act(self):
        out = []
        for lock, regions in self.regions.items():
            for i, first in enumerate(regions):
                for later in regions[i + 1:]:
                    for attr, check_line in first.checked.items():
                        if attr in later.writes:
                            out.append(Finding(
                                self.mod.rel, later.writes[attr], RULE,
                                f"split check-then-act on '{attr}' in "
                                f"{self.fn.name}(): checked under "
                                f"{lock} at line {check_line} but "
                                f"mutated in a separate lock region — "
                                f"the check may be stale; merge the "
                                f"regions or re-validate before "
                                f"mutating"))
        return out


def _functions_of(tree):
    """[(class_name_or_None, fn_node, enclosing_fns)] for every def in
    the module, attributed to its innermost enclosing class and its
    chain of lexically enclosing functions (outermost first)."""
    out = []

    def walk(node, cls, parents):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name, parents)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                out.append((cls, child, tuple(parents)))
                walk(child, cls, parents + [child])
            else:
                walk(child, cls, parents)

    walk(tree, None, [])
    return out


def _local_guards(mod, fn):
    """Annotated ``name = ...  # guarded-by: <lock>`` declarations in
    ``fn``'s own body (nested defs excluded): {name: (lock, decl_line)}.
    Closure state shared with worker threads is declared this way
    (e.g. a results dict guarded by a Condition)."""
    nested = set()
    for sub in ast.walk(fn):
        if sub is not fn and isinstance(sub, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
            nested.update(ast.walk(sub))
    out = {}
    for node in ast.walk(fn):
        if node in nested:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target]
                   if isinstance(node, (ast.AnnAssign, ast.AugAssign))
                   else [])
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                lock = _line_guard(mod, node.lineno)
                if lock:
                    out[tgt.id] = (lock, node.lineno)
    return out


def _find_cycles(edges):
    """Minimal cycle listing over the acquisition-order digraph."""
    graph = {}
    for a, b, _ in edges:
        graph.setdefault(a, set()).add(b)
    cycles, seen_cycles = [], set()

    def dfs(node, stack, on_stack):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
            elif (node, nxt) not in visited_edges:
                visited_edges.add((node, nxt))
                stack.append(nxt)
                on_stack.add(nxt)
                dfs(nxt, stack, on_stack)
                on_stack.discard(nxt)
                stack.pop()

    visited_edges = set()
    for start in sorted(graph):
        dfs(start, [start], {start})
    return cycles


def analyze_module(mod, global_edges):
    """All lock-discipline findings for one module; nested-lock edges
    are appended to ``global_edges`` for the cross-module graph."""
    tree = mod.tree
    if tree is None:
        return []
    class_guards, global_guards = collect_guards(mod)
    functions = _functions_of(tree)
    locals_of = {fn: _local_guards(mod, fn) for _, fn, _ in functions}
    if not class_guards and not global_guards and \
            not any(locals_of.values()):
        return []
    findings = []
    for cls_name, fn, parents in functions:
        if fn.name in _EXEMPT_METHODS:
            continue
        attr_guards = class_guards.get(cls_name, {}) if cls_name else {}
        # closure state annotated in an enclosing function is shared
        # with this one; its own declarations are exempt at decl line
        name_guards = dict(global_guards)
        decl_lines = {}
        for enclosing in parents + (fn,):
            for name, (lock, line) in locals_of.get(enclosing,
                                                    {}).items():
                name_guards[name] = lock
                if enclosing is fn:
                    decl_lines[name] = line
        if not attr_guards and not name_guards:
            continue

        def qualify(lock_expr, _cls=cls_name):
            if lock_expr.startswith("self."):
                return f"{mod.rel}::{_cls}.{lock_expr[5:]}"
            return f"{mod.rel}::{lock_expr}"

        analyzer = _FunctionAnalyzer(
            mod, fn, attr_guards, name_guards, qualify,
            assume_all_held=fn.name.endswith("_locked"),
            decl_lines=decl_lines).run()
        findings.extend(analyzer.findings)
        findings.extend(analyzer.check_then_act())
        global_edges.extend(analyzer.edges)
    return findings


@register(RULE, "guarded-by attrs locked; no lock cycles / split CTA")
def find(project):
    findings, edges = [], []
    for mod in project.modules():
        findings.extend(analyze_module(mod, edges))
    for cyc in _find_cycles(edges):
        # anchor the cycle finding at one contributing edge's site
        a, b = cyc[0], cyc[1]
        where = next(((m_a, m_b, ln) for m_a, m_b, ln in edges
                      if m_a == a and m_b == b), None)
        rel, lineno = ("", 0)
        if where is not None:
            rel = where[0].split("::", 1)[0]
            lineno = where[2]
        findings.append(Finding(
            rel, lineno, RULE,
            "lock-order cycle (deadlock hazard): " + " -> ".join(cyc)
            + " — acquire these locks in one global order"))
    return findings


def lock_order_edges(project):
    """The acquisition-order edge list ``[(from, to, lineno)]`` — bench
    and tests introspect the graph without re-running the whole pass."""
    edges = []
    for mod in project.modules():
        analyze_module(mod, edges)
    return edges
