"""Rule ``metric-names``: metric names registered under paddle_tpu/
must follow Prometheus naming conventions.

Statically scanned rules (literal first-argument names to ``Counter(``
/ ``Gauge(`` / ``Histogram(`` and ``registry.counter(`` & co.):

- names are ``snake_case`` (``^[a-z][a-z0-9_]*$``);
- counter names end in ``_total``;
- a name never appears with two different metric kinds;
- unit suffixes are canonical (``_seconds``/``_bytes``/``_ratio``; no
  ``_s``/``_ms``/``_kb``/... abbreviations on gauges or histograms);
- a histogram name must END in a canonical unit suffix.

SLO/alert identifiers (AST-scanned calls to ``SLO(`` and
``BurnRateAlert(``) follow the same discipline:

- the slo ``name`` literal is ``snake_case`` (it becomes the ``slo``
  label value on every ``slo_*`` series);
- keyword parameters never abbreviate their unit — ``_s``/``_ms``/...
  kwargs (``window_s=``, ``clear_after_s=``) are rejected, seconds are
  spelled out (``short_window_seconds=``) per the unit rule above;
- a literal alert ``severity`` comes from the fixed enum
  (``"page"``/``"ticket"`` — ``observability.slo.SEVERITIES``).

The continuous profiler's self-telemetry is a pinned contract: any
literal metric name starting with ``profiling_`` must come from the
``_PROFILING_SERIES`` set (mirroring
``observability.profiling.PROFILING_SERIES``) — a new sampler series
is added in both places or not at all.
"""
from __future__ import annotations

import ast
import re
import sys

from tools.analysis.core import (Finding, Project, apply_suppressions,
                                 register)

# Counter("name"...) / Gauge( / Histogram(  — constructor form — and
# <registry>.counter("name"...) / .gauge( / .histogram( — get-or-create
# form.  Only literal names are checkable statically; a variable name
# is skipped (there are none today — keep it that way).
_METRIC_CALL = re.compile(
    r"""(?:\b(?P<cls>Counter|Gauge|Histogram)
         |\.(?P<meth>counter|gauge|histogram))
        \s*\(\s*(?P<q>['"])(?P<name>[^'"]+)(?P=q)""", re.VERBOSE)

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")

# canonical unit suffixes for quantity-bearing series
_UNIT_SUFFIXES = ("_seconds", "_bytes", "_ratio")
# abbreviated / non-canonical unit spellings that MUST NOT end a gauge
# or histogram name
_BAD_UNIT = re.compile(
    r"_(s|sec|secs|ms|millis|micros|us|ns|min|mins|minutes|hr|hrs|"
    r"hours|kb|mb|gb|tb|kib|mib|gib|pct|percent)$")

RULE = "metric-names"

# the SLO/alert declaration calls the AST scan covers
_SLO_CALLS = ("SLO", "BurnRateAlert")
# mirrors observability.slo.SEVERITIES — the pass must not import the
# package it analyses, so the enum is pinned here and a self-test in
# the suite keeps the two in sync
_SEVERITIES = ("page", "ticket")
# mirrors observability.profiling.PROFILING_SERIES — same pinning
# discipline as _SEVERITIES: the pass must not import the package it
# analyses, so the sampler's self-telemetry surface is pinned here and
# a suite self-test keeps the two in sync.  A new profiling_* series
# is added in both places, deliberately, or not at all.
_PROFILING_SERIES = (
    "profiling_samples_total",
    "profiling_sample_seconds",
    "profiling_captures_total",
    "profiling_captures_suppressed_total",
    "profiling_capture_active",
    "profiling_overhead_ratio",
)
# abbreviated unit suffixes rejected on SLO/alert kwarg names (the
# kwarg-shaped twin of _BAD_UNIT): windows and horizons spell seconds
# out — short_window_seconds, never short_window_s
_BAD_KWARG_UNIT = re.compile(
    r"_(s|sec|secs|ms|millis|micros|us|ns|min|mins|hr|hrs)$")


def _stripped_code(mod):
    """Whole-file text with per-line comments removed — a call split
    across lines (``Counter(\\n  "name")``) must still be seen."""
    return "\n".join(line.split("#", 1)[0] for line in mod.lines)


def _str_const(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _slo_findings(mod, out):
    """AST scan for SLO(/BurnRateAlert( declarations: snake_case slo
    names, spelled-out unit kwargs, enum severities.  Only literal
    values are checkable statically; variables are skipped."""
    tree = mod.tree
    if tree is None:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = (func.id if isinstance(func, ast.Name)
                 else func.attr if isinstance(func, ast.Attribute)
                 else None)
        if fname not in _SLO_CALLS:
            continue

        def f(msg, _l=node.lineno):
            out.append(Finding(mod.rel, _l, RULE, msg))

        first = _str_const(node.args[0]) if node.args else None
        if fname == "SLO" and first is not None and \
                not _SNAKE.match(first):
            f(f"slo name {first!r} is not snake_case")
        if fname == "BurnRateAlert" and first is not None and \
                first not in _SEVERITIES:
            f(f"alert severity {first!r} is not in the fixed enum "
              f"{_SEVERITIES}")
        for kw in node.keywords:
            if kw.arg is None:
                continue
            sval = _str_const(kw.value)
            if kw.arg == "name" and fname == "SLO" and \
                    sval is not None and not _SNAKE.match(sval):
                f(f"slo name {sval!r} is not snake_case")
            if kw.arg == "severity" and sval is not None and \
                    sval not in _SEVERITIES:
                f(f"alert severity {sval!r} is not in the fixed enum "
                  f"{_SEVERITIES}")
            m_bad = _BAD_KWARG_UNIT.search(kw.arg)
            if m_bad:
                f(f"{fname} parameter {kw.arg!r} abbreviates its unit "
                  f"suffix '_{m_bad.group(1)}' — spell it out "
                  f"(..._seconds)")


@register(RULE, "Prometheus naming conventions on metric literals")
def find(project):
    out = []
    seen = {}                    # name -> (kind, "file:line")
    for mod in project.modules():
        _slo_findings(mod, out)
        code = _stripped_code(mod)
        for m in _METRIC_CALL.finditer(code):
            kind = (m.group("cls") or m.group("meth")).lower()
            name = m.group("name")
            lineno = code.count("\n", 0, m.start()) + 1

            def f(msg, _l=lineno, _m=mod):
                out.append(Finding(_m.rel, _l, RULE, msg))

            if not _SNAKE.match(name):
                f(f"metric name {name!r} is not snake_case")
            if name.startswith("profiling_") and \
                    name not in _PROFILING_SERIES:
                f(f"profiling series {name!r} is not in the pinned "
                  f"contract set — extend _PROFILING_SERIES here AND "
                  f"observability.profiling.PROFILING_SERIES together")
            if kind == "counter" and not name.endswith("_total"):
                f(f"counter {name!r} must end in '_total' "
                  f"(Prometheus convention)")
            if kind in ("gauge", "histogram"):
                m_bad = _BAD_UNIT.search(name)
                if m_bad:
                    f(f"{kind} {name!r} uses the non-canonical unit "
                      f"suffix '_{m_bad.group(1)}' — spell it out "
                      f"({'/'.join(_UNIT_SUFFIXES)})")
                elif kind == "histogram" and \
                        not name.endswith(_UNIT_SUFFIXES):
                    f(f"histogram {name!r} must end in a canonical "
                      f"unit suffix ({'/'.join(_UNIT_SUFFIXES)})")
            prev = seen.get(name)
            if prev is not None and prev[0] != kind:
                f(f"{name!r} registered as {kind} but as {prev[0]} "
                  f"at {prev[1]} — one name, one type")
            else:
                seen.setdefault(name, (kind, f"{mod.rel}:{lineno}"))
    return out


# ------------------------------------------------- legacy shim surface

def check(root=None):
    """Old-format list ``['paddle_tpu/<rel>:<line>: <problem>']``."""
    project = Project(package_root=root) if root else Project()
    return [f"{f.file if f.file.startswith('paddle_tpu/') else 'paddle_tpu/' + f.file.split('/', 1)[-1]}"
            f":{f.line}: {f.message}"
            for f in apply_suppressions(project, find(project))]


def main(argv=None):
    violations = check(argv[0] if argv else None)
    if violations:
        print("metric naming violations "
              "(Prometheus conventions, see tools/check_metric_names.py):",
              file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("check_metric_names: OK")
    return 0
