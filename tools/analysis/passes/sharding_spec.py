"""Rule ``sharding-spec``: statically validate the mesh layer's
PartitionSpec surface.

``distributed/mesh.py`` made the PartitionSpec rule table and the
named-axis mesh the single multi-chip contract — which means a typo'd
axis name, a duplicated axis, a donate/sharding arity slip at a jit
site, or a rule shadowed into deadness all compile fine and only
surface as a wrong (or silently replicated) layout on real hardware.
Four checks, one rule id:

- **unknown axis** — every axis named in a ``P(...)`` /
  ``PartitionSpec(...)`` literal must be declared by some mesh in the
  package: ``mesh.AXIS_ORDER`` plus every literal axis tuple passed to
  a ``Mesh(...)`` constructor (the hybrid engine's ``sep``/``ep``
  axes live there).  ``resolve_spec`` prunes unknown axes to
  *replication* at runtime — a typo doesn't error, it silently stops
  sharding.
- **duplicate axis** — one mesh axis may shard at most one dimension
  of an array; ``P("mp", "mp")`` (including inside tuple entries) is
  rejected by jax only at trace time, on hardware.
- **donate/sharding arity** — a ``jax.jit`` call carrying both
  ``in_shardings`` and ``donate_argnums`` (directly, via a kwargs
  dict literal, or via ``d.update(...)`` / ``d["k"] = ...`` on one)
  must keep every donated index inside the in_shardings tuple;
  statically-resolvable mismatches are flagged (variables that can't
  be resolved one assignment deep are skipped, not guessed).
- **dead rule** — rule tables (module-level tuples of
  ``(pattern, P(...))``) are matched first-match-wins; a rule whose
  own sample matches are all captured by earlier rules can never fire.
  Samples are generated from the pattern's parse tree (branches,
  optional parts, char classes), so ``(^|[/_])wte$``-style patterns
  are exercised, not string-hacked.  Unparseable patterns and rule
  tables referenced nowhere else in the package are also flagged.

Suppress a vetted site with ``# lint-ok: sharding-spec <reason>``.
"""
from __future__ import annotations

import ast
import re

from tools.analysis.core import Finding, register

RULE = "sharding-spec"


# ------------------------------------------------------- axis universe


def _axis_order_of(mod):
    """The AXIS_ORDER literal of one module, or None."""
    tree = mod.tree
    if tree is None:
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "AXIS_ORDER" \
                        and isinstance(node.value, (ast.Tuple, ast.List)):
                    vals = [e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)]
                    if vals:
                        return tuple(vals)
    return None


def _declared_axes(project):
    """Union of every axis a mesh in the package declares: AXIS_ORDER
    plus literal axis tuples handed to ``Mesh(...)``.  Empty set means
    the project declares no meshes — the axis check then stays silent
    (nothing to validate against)."""
    axes = set()
    for mod in project.modules():
        # cheap text gate: most modules declare no mesh at all
        if "AXIS_ORDER" not in mod.text and "Mesh(" not in mod.text:
            continue
        order = _axis_order_of(mod)
        if order:
            axes.update(order)
        tree = mod.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = node.func.attr if isinstance(node.func, ast.Attribute) \
                else node.func.id if isinstance(node.func, ast.Name) \
                else None
            if name != "Mesh":
                continue
            for arg in list(node.args[1:2]) + [
                    kw.value for kw in node.keywords
                    if kw.arg == "axis_names"]:
                if isinstance(arg, (ast.Tuple, ast.List)):
                    axes.update(e.value for e in arg.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str))
    return axes


# ------------------------------------------------------ spec literals


def _pspec_aliases(mod):
    """Local names under which PartitionSpec is importable in ``mod``
    (``P``, ``PartitionSpec``, custom aliases)."""
    aliases = set()
    tree = mod.tree
    if tree is None:
        return aliases
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "PartitionSpec":
                    aliases.add(a.asname or a.name)
    return aliases


def _spec_axes(call):
    """[(axis_name, lineno)] for every string axis in one P(...) call
    (tuple entries flattened)."""
    out = []
    for arg in call.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, arg.lineno))
        elif isinstance(arg, (ast.Tuple, ast.List)):
            for e in arg.elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, str):
                    out.append((e.value, e.lineno))
    return out


def _check_spec_literals(mod, axes, findings):
    if "PartitionSpec" not in mod.text:
        return
    aliases = _pspec_aliases(mod)
    if not aliases:
        return
    tree = mod.tree
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = node.func.id if isinstance(node.func, ast.Name) else \
            node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        if name not in aliases:
            continue
        named = _spec_axes(node)
        seen = {}
        for ax, lineno in named:
            if axes and ax not in axes:
                findings.append(Finding(
                    mod.rel, lineno, RULE,
                    f"unknown mesh axis '{ax}' in PartitionSpec — no "
                    f"mesh in the package declares it (known: "
                    f"{sorted(axes)}); resolve_spec silently degrades "
                    f"it to replication"))
            if ax in seen:
                findings.append(Finding(
                    mod.rel, lineno, RULE,
                    f"axis '{ax}' appears twice in one PartitionSpec "
                    f"— a mesh axis may shard at most one dimension"))
            seen[ax] = lineno


# ------------------------------------------------- donate/sharding arity


def _tuple_len(node):
    """Static length of a tuple expression (literals, + concat,
    * int), or None when unresolvable."""
    if isinstance(node, (ast.Tuple, ast.List)):
        if any(isinstance(e, ast.Starred) for e in node.elts):
            return None
        return len(node.elts)
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Add):
            a, b = _tuple_len(node.left), _tuple_len(node.right)
            return None if a is None or b is None else a + b
        if isinstance(node.op, ast.Mult):
            if isinstance(node.right, ast.Constant) and \
                    isinstance(node.right.value, int):
                a = _tuple_len(node.left)
                return None if a is None else a * node.right.value
            if isinstance(node.left, ast.Constant) and \
                    isinstance(node.left.value, int):
                b = _tuple_len(node.right)
                return None if b is None else b * node.left.value
    return None


def _donate_indices(node):
    """Static donated-argnum indices, or None when unresolvable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return out
    return None


def _is_jit_call(node):
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr == "jit"
    if isinstance(fn, ast.Name):
        return fn.id == "jit"
    return False


def _jit_kw_sources(fn):
    """For each function: map kwargs-dict variable name ->
    {key: value expr} accumulated from dict literals, ``dict(...)``
    constructors, ``d["k"] = v`` and ``d.update(k=v)``."""
    dicts = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
            if isinstance(value, ast.Dict):
                entry = dicts.setdefault(name, {})
                for k, v in zip(value.keys, value.values):
                    if isinstance(k, ast.Constant):
                        entry[k.value] = v
            elif isinstance(value, ast.Call) and \
                    isinstance(value.func, ast.Name) and \
                    value.func.id == "dict":
                entry = dicts.setdefault(name, {})
                for kw in value.keywords:
                    if kw.arg:
                        entry[kw.arg] = kw.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Subscript) and \
                isinstance(node.targets[0].value, ast.Name):
            sub = node.targets[0]
            if isinstance(sub.slice, ast.Constant):
                dicts.setdefault(sub.value.id, {})[
                    sub.slice.value] = node.value
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "update" and \
                isinstance(node.func.value, ast.Name):
            entry = dicts.setdefault(node.func.value.id, {})
            for kw in node.keywords:
                if kw.arg:
                    entry[kw.arg] = kw.value
    return dicts


def _locals_map(fn):
    """Simple one-hop local assignments: name -> value expr."""
    out = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
    return out


def _check_jit_sites(mod, findings):
    # arity only matters where a jit call names shardings AND donates
    if "in_shardings" not in mod.text or \
            "donate_argnums" not in mod.text:
        return
    tree = mod.tree
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in fns + [tree]:
        kw_dicts = _jit_kw_sources(fn) if fn is not tree else {}
        local_vals = _locals_map(fn) if fn is not tree else {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or not _is_jit_call(node):
                continue
            shard_expr, donate_expr = None, None
            for kw in node.keywords:
                if kw.arg == "in_shardings":
                    shard_expr = kw.value
                elif kw.arg == "donate_argnums":
                    donate_expr = kw.value
                elif kw.arg is None and isinstance(kw.value, ast.Name):
                    src = kw_dicts.get(kw.value.id)
                    if src:
                        shard_expr = shard_expr or \
                            src.get("in_shardings")
                        donate_expr = donate_expr or \
                            src.get("donate_argnums")
            if shard_expr is None or donate_expr is None:
                continue
            # resolve bare-name operands one assignment deep
            if isinstance(shard_expr, ast.Name):
                shard_expr = local_vals.get(shard_expr.id, shard_expr)
            if isinstance(donate_expr, ast.Name):
                donate_expr = local_vals.get(donate_expr.id,
                                             donate_expr)
            n_shard = _tuple_len(shard_expr)
            donated = _donate_indices(donate_expr)
            if n_shard is None or donated is None:
                continue        # not statically resolvable: skip
            bad = [d for d in donated if d >= n_shard]
            if bad:
                findings.append(Finding(
                    mod.rel, node.lineno, RULE,
                    f"donate/sharding arity mismatch at jax.jit site: "
                    f"donate_argnums {sorted(donated)} donates "
                    f"argument(s) {sorted(bad)} but in_shardings "
                    f"covers only {n_shard} argument(s) — the donated "
                    f"buffer has no declared layout"))


# ---------------------------------------------------------- rule tables


def _sample_strings(pattern, cap=16):
    """Small set of strings matching ``pattern``, generated from its
    parse tree.  Handles the constructs rule tables use: literals,
    branches, optional subpatterns, char classes, anchors.  Returns []
    when generation fails (pattern too rich — the check then skips)."""
    try:
        import re._parser as sre_parse      # py >= 3.11
    except ImportError:                     # pragma: no cover
        import sre_parse
    try:
        parsed = sre_parse.parse(pattern)
    except re.error:
        return []

    def gen(tokens):
        outs = [""]
        for op, av in tokens:
            op = str(op).lower().rsplit(".", 1)[-1]
            if op == "literal":
                outs = [o + chr(av) for o in outs]
            elif op == "in":
                ch = None
                for iop, iav in av:
                    iop = str(iop).lower().rsplit(".", 1)[-1]
                    if iop == "literal":
                        ch = chr(iav)
                        break
                    if iop == "range":
                        ch = chr(iav[0])
                        break
                    if iop == "category":
                        cat = str(iav).lower()
                        ch = "0" if "digit" in cat else "a"
                        break
                if ch is None:
                    return None
                outs = [o + ch for o in outs]
            elif op == "max_repeat" or op == "min_repeat":
                lo, hi, sub = av
                subs = gen(sub)
                if subs is None:
                    return None
                variants = []
                counts = {lo, min(hi, max(lo, 1))}
                for n in sorted(counts):
                    for s in subs:
                        variants.append(s * n)
                outs = [o + v for o in outs for v in variants][:cap]
            elif op == "branch":
                _, branches = av
                variants = []
                for b in branches:
                    subs = gen(b)
                    if subs is None:
                        return None
                    variants.extend(subs)
                outs = [o + v for o in outs for v in variants][:cap]
            elif op == "subpattern":
                sub = av[-1]
                subs = gen(sub)
                if subs is None:
                    return None
                outs = [o + v for o in outs for v in subs][:cap]
            elif op == "at":
                continue                     # anchors add nothing
            elif op == "any":
                outs = [o + "x" for o in outs]
            else:
                return None
        return outs[:cap]

    out = gen(parsed)
    if not out:
        return []
    # anchored '(^|[/_])' samples may start with '^' behavior — filter
    # to strings the pattern actually matches
    return [s for s in out if re.search(pattern, s)]


def _rule_tables(mod):
    """[(table_name, lineno, [(pattern, lineno)])] — module-level
    tuples/lists of ``(str_const, Call)`` pairs."""
    if "PartitionSpec" not in mod.text:
        return []
    tree = mod.tree
    out = []
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        value = node.value
        if not isinstance(value, (ast.Tuple, ast.List)) or \
                not value.elts:
            continue
        rules = []
        for e in value.elts:
            if isinstance(e, (ast.Tuple, ast.List)) and \
                    len(e.elts) == 2 and \
                    isinstance(e.elts[0], ast.Constant) and \
                    isinstance(e.elts[0].value, str) and \
                    isinstance(e.elts[1], ast.Call):
                rules.append((e.elts[0].value, e.elts[0].lineno))
            else:
                rules = []
                break
        if rules:
            out.append((node.targets[0].id, node.lineno, rules))
    return out


def _check_rule_tables(project, mod, findings):
    tables = _rule_tables(mod)
    if not tables:
        return
    for table_name, table_line, rules in tables:
        # referenced anywhere else? (own declaration line excluded)
        referenced = False
        for other in project.modules():
            text = other.text
            if other is mod:
                hits = [m for m in re.finditer(
                    rf"\b{re.escape(table_name)}\b", text)]
                own = len(mod.line_at(table_line))
                referenced = any(
                    text[:m.start()].count("\n") + 1 != table_line
                    for m in hits)
            elif re.search(rf"\b{re.escape(table_name)}\b", text):
                referenced = True
            if referenced:
                break
        if not referenced:
            findings.append(Finding(
                mod.rel, table_line, RULE,
                f"rule table '{table_name}' is referenced nowhere — "
                f"dead table; wire it into resolve_spec/param_specs "
                f"or delete it"))
        compiled = []
        for pattern, lineno in rules:
            try:
                rx = re.compile(pattern)
            except re.error as e:
                findings.append(Finding(
                    mod.rel, lineno, RULE,
                    f"rule pattern {pattern!r} does not compile: {e}"))
                compiled.append(None)
                continue
            compiled.append(rx)
            samples = _sample_strings(pattern)
            if not samples:
                continue
            shadowed_by = None
            for j, earlier in enumerate(compiled[:-1]):
                if earlier is None:
                    continue
                if all(earlier.search(s) for s in samples):
                    shadowed_by = rules[j][0]
                    break
            if shadowed_by is not None:
                findings.append(Finding(
                    mod.rel, lineno, RULE,
                    f"dead rule: pattern {pattern!r} can never win — "
                    f"every match is captured first by earlier rule "
                    f"{shadowed_by!r} (first match wins); reorder or "
                    f"remove it"))


# ---------------------------------------------------------------- driver


@register(RULE, "PartitionSpecs use real axes; jit/rule tables coherent")
def find(project):
    axes = _declared_axes(project)
    findings = []
    for mod in project.scoped_modules():
        if mod.tree is None:
            continue
        _check_spec_literals(mod, axes, findings)
        _check_jit_sites(mod, findings)
        _check_rule_tables(project, mod, findings)
    # the jit walk visits module scope and each function scope; a call
    # seen from both produces the identical finding twice — dedupe
    seen, out = set(), []
    for f in findings:
        key = (f.file, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    out.sort(key=lambda f: (f.file, f.line))
    return out


def declared_axes(project):
    """The axis universe the pass validates against — tests/bench
    introspection."""
    return sorted(_declared_axes(project))
