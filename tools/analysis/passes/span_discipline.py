"""Rule ``span-discipline``: every tracer span opened with
``start_trace`` / ``start_span`` must be closed on all paths.

A span that is never ``end()``-ed sits in the tracer's live table
forever: its trace never reaches the completed ring (the flight record
silently loses exactly the request it was opened for) and the live
table grows without bound — a leak the lock-discipline and bounded-ring
guarantees cannot see.  The safe shapes, in preference order:

- **context manager**: ``with tracer.trace(...)`` / ``tracer.span(...)``
  (never start a span these fit), or ``with tracer.start_trace(...)``
  — ``Span.__exit__`` ends on success *and* error paths;
- **chained end**: ``tracer.start_trace(...).end(now)`` (also through
  ``.set_attribute(...)``-style chains) — zero-width or retroactive
  spans;
- **ownership transfer**: the span is stored on an object
  (``req._span = ...``), put in a container, passed to a callee or
  returned — some other lifecycle owns the close (the tracer's
  root-end force-close is the final backstop);
- **explicit end on every path**: a local span whose every function
  exit — fallthrough, ``return``, branch — is preceded by ``.end()``.

Flagged:

- a start call whose result is **discarded** (bare expression
  statement, no chained ``.end``) — the span can never be ended;
- a local span variable that is **never ended** (no ``.end()``, no
  ``with``, no escape) anywhere in the function;
- a ``return`` (or fallthrough) reachable with the span still
  **open** — the paths-analysis is a statement-level walk: branches
  must all close, ``try`` bodies may close in ``finally``, loops are
  credited optimistically.

The analysis is per-function and intentionally optimistic about
escapes (a span passed to any call is assumed handed off), so every
finding is near-certainly real.  Suppress a vetted site with
``# lint-ok: span-discipline <reason>`` on the start line.
"""
from __future__ import annotations

import ast

from tools.analysis.core import Finding, register

RULE = "span-discipline"

_START_ATTRS = {"start_trace", "start_span"}


def _is_start_call(node):
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _START_ATTRS)


def _chain_root(node):
    """The head of an attribute/call chain: for
    ``a.b(...).c(...).end()`` → the ``a`` Name (or the innermost
    start-call for chains rooted at one)."""
    while True:
        if isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Attribute):
            node = node.value
        else:
            return node


def _parent_map(fn_node):
    parents = {}
    for node in ast.walk(fn_node):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _chained_to_end(call, parents):
    """Is ``call`` the root of a chain whose outermost call is
    ``.end(...)``?  Covers ``start_trace(...).end()`` and
    ``start_trace(...).set_attribute(...).end()``-style chains
    (mutators return the span)."""
    node = call
    while True:
        parent = parents.get(node)
        if isinstance(parent, ast.Attribute) and parent.value is node:
            call_parent = parents.get(parent)
            if isinstance(call_parent, ast.Call) and \
                    call_parent.func is parent:
                if parent.attr == "end":
                    return True
                node = call_parent      # chained mutator; keep climbing
                continue
        return False


def _name_refs(node, name):
    return any(isinstance(sub, ast.Name) and sub.id == name
               for sub in ast.walk(node))


def _own_nodes(fn_node):
    """Nodes of this function's own body — nested function/lambda
    bodies are their own analysis units."""
    nested = set()
    for sub in ast.walk(fn_node):
        if sub is fn_node:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            nested.update(ast.walk(sub))
            nested.discard(sub)
    return [n for n in ast.walk(fn_node) if n not in nested]


class _SpanPaths:
    """Statement-level all-paths walk for ONE named local span."""

    def __init__(self, name, open_line, mod, fn_name, nested):
        self.name = name
        self.open_line = open_line
        self.mod = mod
        self.fn_name = fn_name
        self.nested = nested
        self.findings = []

    def _flag(self, line, what):
        self.findings.append(Finding(
            self.mod.rel, self.open_line, RULE,
            f"span '{self.name}' opened here can leave "
            f"{self.fn_name}() un-ended: {what} (line {line})"))

    def _closes(self, stmt):
        """Does this statement (own nodes only) surely end or hand off
        the span?  end()-chain, ``with name``, bare-name call argument,
        return/yield of the name, store into attribute/subscript/
        container, or deletion."""
        for sub in ast.walk(stmt):
            if sub in self.nested:
                continue
            if isinstance(sub, ast.Call):
                root = _chain_root(sub)
                if isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "end" and \
                        isinstance(root, ast.Name) and \
                        root.id == self.name:
                    return True
                for a in list(sub.args) + [kw.value
                                           for kw in sub.keywords]:
                    if isinstance(a, ast.Name) and a.id == self.name:
                        return True     # handed to a callee
            elif isinstance(sub, ast.withitem):
                ce = sub.context_expr
                if isinstance(ce, ast.Name) and ce.id == self.name:
                    return True
            elif isinstance(sub, (ast.Return, ast.Yield)):
                if sub.value is not None and \
                        _name_refs(sub.value, self.name):
                    return True
            elif isinstance(sub, ast.Assign):
                if any(isinstance(t, (ast.Attribute, ast.Subscript,
                                      ast.Tuple, ast.List))
                       for t in sub.targets) and \
                        _name_refs(sub.value, self.name):
                    return True
            elif isinstance(sub, (ast.List, ast.Tuple, ast.Dict,
                                  ast.Set)):
                if _name_refs(sub, self.name):
                    return True         # packed into a container
        return False

    def _opens(self, stmt):
        if isinstance(stmt, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == self.name
                   for t in stmt.targets):
                return any(_is_start_call(sub)
                           for sub in ast.walk(stmt.value))
        return False

    @staticmethod
    def _merge(statuses):
        live = [s for s in statuses if s != "terminated"]
        if not live:
            return "terminated"
        if any(s == "open" for s in live):
            return "open"
        if any(s == "closed" for s in live):
            return "closed"
        return "inactive"

    def walk(self, stmts, status):
        for stmt in stmts:
            if status == "terminated":
                return status           # rest of block unreachable
            if self._opens(stmt):
                # re-open (the name is rebound): a still-open previous
                # span was already flagged when its path escaped
                status = "open"
                continue
            if status == "open" and self._closes(stmt):
                status = "closed"
                continue
            if isinstance(stmt, ast.Return):
                if status == "open":
                    self._flag(stmt.lineno, "return with span open")
                return "terminated"
            if isinstance(stmt, ast.Raise):
                # optimistic: an uncaught raise leaks the span only if
                # no outer finally/root-end catches it — too noisy to
                # flag; try/finally shapes are credited explicitly
                return "terminated"
            if isinstance(stmt, ast.If):
                s1 = self.walk(stmt.body, status)
                s2 = self.walk(stmt.orelse, status)
                status = self._merge([s1, s2])
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                s1 = self.walk(stmt.body, status)
                self.walk(stmt.orelse, s1)
                # optimistic: a loop that closes is credited even
                # though it may run zero times — near-zero noise beats
                # exhaustive zero-trip pessimism
                status = s1 if s1 == "closed" else status
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                status = self.walk(stmt.body, status)
            elif isinstance(stmt, ast.Try):
                s_body = self.walk(stmt.body, status)
                handler_in = (s_body
                              if s_body not in ("inactive", "terminated")
                              else status)
                for h in stmt.handlers:
                    self.walk(h.body, handler_in)
                s_else = self.walk(stmt.orelse, s_body)
                if stmt.finalbody:
                    # finally runs even on return/raise out of the body
                    fin_in = (s_else if s_else != "terminated"
                              else handler_in)
                    status = self.walk(stmt.finalbody, fin_in)
                else:
                    status = s_else
        return status


def _analyze_function(mod, fn_node, fn_name):
    findings = []
    own = _own_nodes(fn_node)
    own_set = set(own)
    nested = {n for n in ast.walk(fn_node) if n not in own_set}
    parents = _parent_map(fn_node)
    tracked = {}        # local name -> first-open line
    for node in own:
        if not _is_start_call(node):
            continue
        if _chained_to_end(node, parents):
            continue
        parent = parents.get(node)
        # climb pure-expression wrappers (IfExp, BoolOp) to the
        # statement/binding that consumes the span
        consumer = parent
        while isinstance(consumer, (ast.IfExp, ast.BoolOp,
                                    ast.NamedExpr)):
            consumer = parents.get(consumer)
        if isinstance(consumer, ast.withitem):
            continue                    # with ...start_trace(...):
        if isinstance(consumer, ast.Call):
            continue                    # argument: handed off at birth
        if isinstance(consumer, (ast.Return, ast.Yield)):
            continue                    # caller owns it
        if isinstance(consumer, ast.Assign):
            targets = consumer.targets
            if len(targets) == 1 and isinstance(targets[0], ast.Name):
                tracked.setdefault(targets[0].id, node.lineno)
                continue
            continue                    # attribute/subscript/tuple store
        if isinstance(consumer, ast.Expr):
            findings.append(Finding(
                mod.rel, node.lineno, RULE,
                f"span result of .{node.func.attr}(...) discarded in "
                f"{fn_name}() — it can never be end()-ed"))
            continue
        # anything else (comparison, f-string, ...) — treat as a
        # handoff; exotic reads don't leak more than the paths walk
        # below would already catch for locals
    for name, line in sorted(tracked.items(), key=lambda kv: kv[1]):
        walker = _SpanPaths(name, line, mod, fn_name, nested)
        final = walker.walk(fn_node.body, "inactive")
        if final == "open":
            walker._flag(fn_node.body[-1].lineno,
                         "fallthrough with span open")
        findings.extend(walker.findings)
    return findings


@register(RULE, "tracer spans ended on all paths")
def find(project):
    out = []
    for mod in project.scoped_modules():
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_analyze_function(mod, node, node.name))
    out.sort(key=lambda f: (f.file, f.line))
    return out
