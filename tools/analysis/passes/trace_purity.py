"""Rule ``trace-purity``: functions reached from a ``jax.jit`` entry
point must be trace-pure.

``jax.jit`` executes the Python body ONCE per input signature; any
host-side effect inside it silently becomes a per-compile (not
per-call) event, and any host sync forces a device round-trip on every
trace.  This pass is the static complement to the runtime compile
watchdog (PR 2) and the invariant PR 10's step replay depends on
(replayed steps must re-trace to bitwise-identical programs).

Entry points: a function literally passed to ``jax.jit(...)`` /
``jit(...)`` (positionally or via ``functools.partial``), or decorated
``@jax.jit`` / ``@partial(jax.jit, ...)``.  The watched-jit idiom
``watch(jax.jit(fn))`` resolves through the inner ``jax.jit`` call.
From each entry the call graph is resolved *within paddle_tpu/*:
lexically enclosing scopes (the entries are mostly closures), module
functions, ``self.method()``, and ``from``-imports between package
modules.  jax/numpy internals are not analyzed.

Flagged inside reached functions:

- **wall-clock reads**: ``time.time/monotonic/perf_counter/...``,
  ``datetime.now`` — a traced timestamp is frozen at compile time;
- **host randomness / global state**: ``random.*``, ``np.random.*``
  (use ``jax.random`` with explicit keys), ``os.environ`` /
  ``os.getenv`` reads;
- **host-sync forcers**: ``.item()`` / ``.tolist()``, ``np.asarray`` /
  ``np.array`` on non-constants, ``float()/int()/bool()`` on traced
  values (shape/ndim/len reads are static and exempt);
- **mutation of nonlocal Python state**: ``global``/``nonlocal``
  declarations, attribute stores (``obj.attr = v``) — the mutation
  happens per-trace, not per-call;
- ``print(...)`` — a compile-time-only side effect that looks like a
  runtime one.

Suppress a vetted site with ``# lint-ok: trace-purity <reason>``.
"""
from __future__ import annotations

import ast

from tools.analysis.core import Finding, register

RULE = "trace-purity"

_CLOCK_CALLS = {("time", "time"), ("time", "monotonic"),
                ("time", "perf_counter"), ("time", "process_time"),
                ("time", "time_ns"), ("time", "monotonic_ns"),
                ("time", "perf_counter_ns"),
                ("datetime", "now"), ("datetime", "utcnow")}

_SYNC_ATTRS = {"item", "tolist"}
_NP_SYNC = {"asarray", "array"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


class _Scope:
    """One lexical scope (module / class / function) with its local
    defs, so ``jax.jit(step)`` can resolve ``step`` outward through
    enclosing functions."""

    def __init__(self, node, parent, cls=None):
        self.node = node
        self.parent = parent
        self.cls = cls                    # innermost enclosing ClassDef
        self.defs = {}                    # name -> _FuncInfo


class _FuncInfo:
    def __init__(self, mod, node, scope, cls):
        self.mod = mod
        self.node = node
        self.scope = scope                # the scope the def CREATES
        self.cls = cls                    # class owning it (methods)

    @property
    def key(self):
        return (self.mod.rel, self.node.lineno, self.node.name)


#: constructors whose module-level result is shared mutable state
_MUTABLE_CTORS = {"dict", "list", "set", "defaultdict", "deque",
                  "OrderedDict", "Counter"}


def _mutable_init(value):
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        name = _dotted(value.func) or ""
        return name.rsplit(".", 1)[-1] in _MUTABLE_CTORS
    return False


class _ModuleIndex:
    """Defs, imports, class methods and mutable globals for one module."""

    def __init__(self, mod):
        self.mod = mod
        self.import_alias = {}            # alias -> module name
        self.from_imports = {}            # name -> (module, original)
        self.top = _Scope(mod.tree, None)
        self.methods = {}                 # (class, name) -> _FuncInfo
        self.functions = []               # every _FuncInfo in the file
        self.mutable_globals = set()      # module-level dict/list/set names
        if mod.tree is not None:
            for node in mod.tree.body:
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target]
                           if isinstance(node, ast.AnnAssign) else [])
                for tgt in targets:
                    if isinstance(tgt, ast.Name) and \
                            _mutable_init(getattr(node, "value", None)):
                        self.mutable_globals.add(tgt.id)
            self._index(mod.tree, self.top, cls=None)

    def _index(self, node, scope, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Import):
                for a in child.names:
                    self.import_alias[a.asname or
                                      a.name.split(".")[0]] = a.name
            elif isinstance(child, ast.ImportFrom):
                for a in child.names:
                    self.from_imports[a.asname or a.name] = (
                        child.module or "", a.name)
            elif isinstance(child, ast.ClassDef):
                self._index(child, scope, cls=child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                info = _FuncInfo(self.mod, child,
                                 _Scope(child, scope, cls), cls)
                info.scope.defs = {}
                scope_defs = scope.defs
                scope_defs[child.name] = info
                if cls is not None:
                    self.methods[(cls, child.name)] = info
                self.functions.append(info)
                self._index(child, info.scope, cls)
            else:
                self._index(child, scope, cls)


def _dotted(node):
    """'a.b.c' for an attribute chain of Names, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_callee(node, index):
    """Does this Call's func denote jax.jit (directly or aliased)?"""
    name = _dotted(node.func)
    if name is None:
        return False
    if name in ("jax.jit", "jit"):
        # `jit` must actually come from jax for the bare spelling
        if name == "jit":
            src = index.from_imports.get("jit")
            return bool(src and src[0].startswith("jax"))
        return True
    # alias: `import jax as j` -> j.jit
    head, _, tail = name.partition(".")
    return tail == "jit" and index.import_alias.get(head) == "jax"


def _jit_fn_args(call):
    """Candidate function expressions passed to one jax.jit call —
    unwraps ``functools.partial(fn, ...)``."""
    args = list(call.args) + [kw.value for kw in call.keywords
                              if kw.arg in ("fun", "fn")]
    out = []
    for a in args[:1]:
        if isinstance(a, ast.Call) and \
                (_dotted(a.func) or "").endswith("partial") and a.args:
            out.append(a.args[0])
        else:
            out.append(a)
    return out


def _resolve_name(name, scope):
    """Look ``name`` up through lexically enclosing scopes."""
    while scope is not None:
        if name in scope.defs:
            return scope.defs[name]
        scope = scope.parent
    return None


class _Graph:
    """Cross-module resolution helpers."""

    def __init__(self, project):
        self.project = project
        self.indexes = {}
        for mod in project.modules():
            if mod.tree is not None:
                self.indexes[mod.rel] = _ModuleIndex(mod)
        # module name ("paddle_tpu.observability.metrics") -> index
        self.by_modname = {}
        for rel, idx in self.indexes.items():
            name = rel[:-3].replace("/", ".")
            if name.endswith(".__init__"):
                name = name[:-len(".__init__")]
            self.by_modname[name] = idx

    def resolve_import(self, index, name):
        """A from-import of ``name`` that lands on a def in another
        package module."""
        src = index.from_imports.get(name)
        if not src:
            return None
        module, orig = src
        # relative imports: fall back to suffix match on module name
        candidates = [module, f"paddle_tpu.{module}"] if module else []
        for cand in candidates:
            idx = self.by_modname.get(cand)
            if idx and orig in idx.top.defs:
                return idx.top.defs[orig]
        if module:
            for modname, idx in self.by_modname.items():
                if modname.endswith(module) and orig in idx.top.defs:
                    return idx.top.defs[orig]
        return None

    def resolve_call(self, call, info):
        """Best-effort: the _FuncInfo a Call lands on, or None."""
        index = self.indexes[info.mod.rel]
        fn = call.func
        if isinstance(fn, ast.Name):
            target = _resolve_name(fn.id, info.scope)
            if target is not None:
                return target
            return self.resolve_import(index, fn.id)
        if isinstance(fn, ast.Attribute):
            # self.method()
            if isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                    and info.cls is not None:
                return index.methods.get((info.cls, fn.attr))
            # module.func() through an import alias
            dotted = _dotted(fn)
            if dotted:
                head, _, tail = dotted.rpartition(".")
                alias_target = index.import_alias.get(head)
                idx = self.by_modname.get(alias_target or head)
                if idx and tail in idx.top.defs:
                    return idx.top.defs[tail]
        return None

    def resolve_fn_expr(self, expr, info_or_index, scope):
        """The _FuncInfo a function-valued expression denotes."""
        index = (info_or_index if isinstance(info_or_index, _ModuleIndex)
                 else self.indexes[info_or_index.mod.rel])
        if isinstance(expr, ast.Name):
            target = _resolve_name(expr.id, scope)
            if target is not None:
                return target
            return self.resolve_import(index, expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            if expr.value.id == "self":
                for (cls, name), m in index.methods.items():
                    if name == expr.attr:
                        return m
        return None


def _entry_points(graph):
    """Every _FuncInfo passed to / decorated with jax.jit."""
    entries = []
    for rel, index in graph.indexes.items():
        if index.mod.tree is None:
            continue
        # decorator form
        for info in index.functions:
            for dec in info.node.decorator_list:
                name = _dotted(dec.func if isinstance(dec, ast.Call)
                               else dec) or ""
                is_jit = name == "jax.jit" or (
                    name == "jit"
                    and (index.from_imports.get("jit") or ("",))[0]
                    .startswith("jax"))
                is_partial_jit = (
                    isinstance(dec, ast.Call)
                    and name.endswith("partial") and dec.args
                    and (_dotted(dec.args[0]) or "") in
                    ("jax.jit", "jit"))
                if is_jit or is_partial_jit:
                    entries.append(info)
        # call form: jax.jit(fn) anywhere, resolved in its scope
        scope_of = {}

        def map_scopes(node, scope):
            for child in ast.iter_child_nodes(node):
                created = scope
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    for f in index.functions:
                        if f.node is child:
                            created = f.scope
                            break
                scope_of[child] = scope
                map_scopes(child, created)

        map_scopes(index.mod.tree, index.top)
        for node, scope in scope_of.items():
            if isinstance(node, ast.Call) and \
                    _is_jit_callee(node, index):
                for fexpr in _jit_fn_args(node):
                    target = graph.resolve_fn_expr(fexpr, index, scope)
                    if target is not None:
                        entries.append(target)
    return entries


def _reachable(graph, entries):
    seen, queue = {}, list(entries)
    for e in entries:
        seen[e.key] = e
    while queue:
        info = queue.pop()
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                target = graph.resolve_call(node, info)
                if target is not None and target.key not in seen:
                    seen[target.key] = target
                    queue.append(target)
    return list(seen.values())


def _impurities(info, index):
    """Findings for one reached function (its own body only — nested
    defs are separate graph nodes)."""
    out = []
    mod = info.mod
    # nodes belonging to defs/lambdas nested inside this function —
    # they are separate call-graph nodes, analyzed only if reached
    nested = set()
    for sub in ast.walk(info.node):
        if sub is info.node:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            nested.update(ast.walk(sub))

    def flag(node, msg):
        out.append(Finding(
            mod.rel, node.lineno, RULE,
            f"{msg} inside jitted call graph "
            f"(reached via {info.node.name}())"))

    # names bound locally (params, assignments) shadow module globals
    local_names = {a.arg for a in info.node.args.args}
    local_names.update(a.arg for a in info.node.args.kwonlyargs)
    for extra in (info.node.args.vararg, info.node.args.kwarg):
        if extra is not None:
            local_names.add(extra.arg)
    for node in ast.walk(info.node):
        if node in nested:
            continue
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Store):
            local_names.add(node.id)

    for node in ast.walk(info.node):
        if node in nested:
            continue
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and \
                node.id in index.mutable_globals and \
                node.id not in local_names:
            flag(node, f"module-global mutable state '{node.id}' read "
                       f"at trace time (value is frozen into the "
                       f"compiled program)")
        if isinstance(node, ast.Global):
            flag(node, "'global' mutation of module state")
        elif isinstance(node, ast.Nonlocal):
            flag(node, "'nonlocal' mutation of enclosing state")
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            # self.x = ... on a traced path mutates per-trace
            flag(node, f"attribute store '{_dotted(node) or node.attr}"
                       f" = ...' mutates Python object state")
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name:
                head, _, tail = name.rpartition(".")
                pair = (head.rsplit(".", 1)[-1], tail)
                if pair in _CLOCK_CALLS:
                    flag(node, f"wall-clock read '{name}()'")
                    continue
                if head in ("random", "np.random", "numpy.random"):
                    flag(node, f"host randomness '{name}()' (use "
                               f"jax.random with an explicit key)")
                    continue
                if name in ("os.getenv",):
                    flag(node, f"environment read '{name}()'")
                    continue
                if pair[1] in _NP_SYNC and pair[0] in ("np", "numpy",
                                                       "onp"):
                    if not _static_arg(node):
                        flag(node, f"'{name}(...)' forces a host sync "
                                   f"on traced values")
                    continue
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_ATTRS and not node.args:
                flag(node, f"'.{node.func.attr}()' forces a host sync")
                continue
            if isinstance(node.func, ast.Name):
                if node.func.id in ("float", "int", "bool") and \
                        node.args and not _static_arg(node):
                    flag(node, f"'{node.func.id}(...)' on a traced "
                               f"value forces a host sync")
                elif node.func.id == "print":
                    flag(node, "'print(...)' runs at trace time only")
        elif isinstance(node, ast.Subscript) or isinstance(node,
                                                           ast.Attribute):
            dotted = _dotted(node)
            if dotted == "os.environ":
                flag(node, "'os.environ' read")
    return out


def _static_arg(call):
    """True when the call's first arg is statically known (constant,
    len(), .shape/.ndim/... read) — not a traced-value sync."""
    if not call.args:
        return True
    a = call.args[0]
    if isinstance(a, ast.Constant):
        return True
    if isinstance(a, ast.Call):
        inner = _dotted(a.func)
        if inner == "len":
            return True
    if isinstance(a, ast.Attribute) and a.attr in _STATIC_ATTRS:
        return True
    if isinstance(a, ast.Subscript) and \
            isinstance(a.value, ast.Attribute) and \
            a.value.attr in _STATIC_ATTRS:
        return True
    return False


@register(RULE, "jitted call graphs free of clocks/randomness/syncs")
def find(project):
    graph = _Graph(project)
    entries = _entry_points(graph)
    reached = _reachable(graph, entries)
    out = []
    seen = set()
    for info in reached:
        index = graph.indexes[info.mod.rel]
        for f in _impurities(info, index):
            key = (f.file, f.line, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
    out.sort(key=lambda f: (f.file, f.line))
    return out


def traced_functions(project):
    """['rel::qualname'] of every function the pass considers reached
    from a jit entry point — tests and bench introspect coverage."""
    graph = _Graph(project)
    reached = _reachable(graph, _entry_points(graph))
    return sorted(f"{i.mod.rel}::{i.node.name}" for i in reached)
