#!/usr/bin/env python
"""Lint: durable writes under paddle_tpu/ must go through the
resilience layer's tmp+rename helpers.

A file opened for write ('w'/'wb'/'x'/'a'/...) anywhere else is a torn-
file hazard: a crash mid-write corrupts whatever used to be at that
path.  ``paddle_tpu.resilience.atomic.atomic_write`` is the one place
allowed to do it (it owns the tmp+``os.replace`` commit); trace/log
writers are allowlisted — losing half a trace is annoying, losing half
a checkpoint is an outage.

Run directly (exit 1 on violations) or import ``check()`` — a tier-1
test wires it into the suite so a regressing ``open(..., "w")`` fails
CI, not a postmortem.
"""
from __future__ import annotations

import os
import re
import sys

# open(path, "w"/"wb"/"a"/"x"/... ) with the mode as a positional or
# mode= literal; tolerates whitespace and f-string paths on one line
_OPEN_WRITE = re.compile(
    r"""\bopen\s*\(              # open(
        [^()]*?,                 #   first arg (no nested parens)
        \s*(?:mode\s*=\s*)?      #   optional mode=
        (['"])([wax]b?\+?t?)\1   #   'w' 'wb' 'a' 'ab' 'x' ...
    """, re.VERBOSE)

# modules allowed to open files for write directly, relative to the
# package root.  Keep this list SHORT and justified.
ALLOWLIST = {
    # the tmp+rename primitive itself
    "resilience/atomic.py",
    # fault injection truncates files in place by design ('r+b' isn't
    # matched anyway, but keep it pinned here for reviewers)
    "resilience/faults.py",
    # chrome-trace export: an append-style log artifact, not durable
    # state; a torn trace is re-recordable
    "profiler/profiler.py",
    # supervisor child logs: append-style run transcripts (same class
    # as trace exports) — a torn log line is cosmetic, and the file
    # must be open BEFORE the child exists to capture its first bytes
    "resilience/supervisor.py",
}


def check(root=None):
    """Return a list of 'path:line: text' violations."""
    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "paddle_tpu")
    root = os.path.abspath(root)
    violations = []
    for dirpath, _, files in os.walk(root):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            if rel in ALLOWLIST:
                continue
            with open(full, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    code = line.split("#", 1)[0]
                    if _OPEN_WRITE.search(code):
                        violations.append(
                            f"paddle_tpu/{rel}:{lineno}: "
                            f"{line.strip()}")
    return violations


def main(argv=None):
    violations = check(argv[0] if argv else None)
    if violations:
        print("non-atomic file writes (use "
              "paddle_tpu.resilience.atomic.atomic_write):",
              file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("check_atomic_writes: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
