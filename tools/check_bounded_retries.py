#!/usr/bin/env python
"""Compatibility shim: the bounded-retries lint now lives in the
unified static-analysis framework as
:mod:`tools.analysis.passes.bounded_retries` (rule id
``bounded-retries``).  The old module-level ``ALLOWLIST`` is empty —
the sanctioned daemons (supervisor ``_watch``, multiprocess ``_get``)
now carry inline ``# lint-ok: bounded-retries <reason>`` comments.
``check()``/``main()`` keep their old signatures; run the whole suite
with ``python -m tools.analysis``."""
from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analysis.passes.bounded_retries import (  # noqa: E402,F401
    ALLOWLIST, check, find, main)

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
