#!/usr/bin/env python
"""Lint: retry/poll loops under paddle_tpu/ must bound themselves.

An unbounded retry loop is a hang with extra steps: a ``while True``
that sleeps-and-retries around a network / store / engine call turns
one dead peer into a wedged process the supervisor has to SIGKILL.
The framework's contract (``resilience/retry.py``) is that every such
loop is bounded by a :class:`Deadline` or an attempt budget
(``max_attempts``) — this lint enforces it statically.

What is flagged: a ``while True:`` / ``while 1:`` loop whose body
contains a *blocking edge* —

- any ``sleep(...)`` call (``time.sleep``, ``dl.sleep``, ...): the
  signature of a backoff-and-retry loop;
- a call to a blocking primitive by name (``recv``, ``accept``,
  ``connect``, ``poll``, ``serve_forever``, ``urlopen``);
- any call passing a ``timeout=`` keyword (a per-attempt timeout
  inside an unbounded loop still loops forever);
- ``next(<delays>)`` where the argument names a backoff generator
  (``*delay*`` / ``*backoff*``)

— unless the loop also references a *bound*: the ``Deadline`` class or
a deadline-ish variable (``deadline``, ``dl``), a ``.remaining()`` /
``.expired()`` probe, or an attempt budget identifier
(``max_attempts`` / ``attempt`` / ``attempts`` / ``retries``).

Loops shaped ``while not stop_event.is_set():`` are not ``while True``
and are never flagged — that is the sanctioned daemon idiom.  The few
legitimate unbounded watchers (a supervisor that watches its child
until the child exits, the dataloader's worker-liveness poll) are
allowlisted by ``relpath::function``.

Run directly (exit 1 on violations) or import ``check()`` — a tier-1
test wires it into the suite like ``check_atomic_writes``, so a new
bare retry loop cannot land.
"""
from __future__ import annotations

import ast
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

#: unbounded-by-design loops: the supervisor watches its child until
#: the child exits (bounded by the child's lifetime, not a deadline);
#: the multiprocess dataloader polls worker liveness forever when the
#: user asked for no timeout (dead workers raise instead)
ALLOWLIST = {
    ("resilience/supervisor.py", "_watch"),
    ("io/multiprocess.py", "_get"),
}

_BLOCKING_NAMES = {"recv", "recv_into", "accept", "connect", "poll",
                   "serve_forever", "urlopen"}
_BOUND_IDS = {"deadline", "dl", "max_attempts", "attempt", "attempts",
              "retries"}
_BOUND_ATTRS = {"remaining", "expired"}


def _iter_py(root):
    for dirpath, _, files in os.walk(root):
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def _call_name(node):
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _is_blocking(loop):
    """Does the loop body contain a blocking-edge call?"""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "sleep" or name in _BLOCKING_NAMES:
            return True
        if any(kw.arg == "timeout" for kw in node.keywords):
            return True
        if name == "next" and node.args:
            arg = node.args[0]
            arg_name = (arg.id if isinstance(arg, ast.Name) else
                        arg.attr if isinstance(arg, ast.Attribute) else "")
            if "delay" in arg_name.lower() or "backoff" in arg_name.lower():
                return True
    return False


def _is_bounded(loop):
    """Does the loop reference a Deadline / attempt budget?"""
    for node in ast.walk(loop):
        if isinstance(node, ast.Name):
            ident = node.id.lower()
            if node.id == "Deadline" or ident in _BOUND_IDS \
                    or "deadline" in ident:
                return True
        elif isinstance(node, ast.Attribute):
            attr = node.attr.lower()
            if attr in _BOUND_ATTRS or attr in _BOUND_IDS \
                    or "deadline" in attr:
                return True
    return False


def _is_forever(test):
    """``while True:`` / ``while 1:`` — a constant-true test."""
    return isinstance(test, ast.Constant) and bool(test.value)


def check(root=None, allowlist=None):
    """Return ['relpath:line in func(): ...'] for every unbounded
    blocking retry loop under ``root`` (default: the paddle_tpu
    package)."""
    if root is None:
        root = os.path.join(HERE, os.pardir, "paddle_tpu")
    root = os.path.abspath(root)
    allow = ALLOWLIST if allowlist is None else set(allowlist)
    violations = []
    for path in _iter_py(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
        # map each while-loop to its innermost enclosing function
        func_of = {}
        for fn in ast.walk(tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for node in ast.walk(fn):
                    if isinstance(node, ast.While):
                        func_of[node] = fn.name   # innermost wins (later)
        for node in ast.walk(tree):
            if not isinstance(node, ast.While) or not _is_forever(node.test):
                continue
            if not _is_blocking(node) or _is_bounded(node):
                continue
            fn_name = func_of.get(node, "<module>")
            if (rel, fn_name) in allow:
                continue
            violations.append(
                f"{rel}:{node.lineno} in {fn_name}(): unbounded "
                f"'while True' around a blocking call — bound it with "
                f"resilience.retry (max_attempts) or a Deadline, or "
                f"allowlist a genuine daemon")
    return sorted(violations)


def main(argv=None):
    violations = check()
    if violations:
        print("unbounded retry/poll loops (see tools/"
              "check_bounded_retries.py):", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("check_bounded_retries: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
