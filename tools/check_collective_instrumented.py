#!/usr/bin/env python
"""Lint: every public op in ``distributed/collective.py`` must route
through the distributed flight recorder.

A collective that isn't recorded is a blind spot exactly where
pod-scale debugging needs eyes: the hang watchdog's desync report can
only name the divergent seq/op if *every* op got a sequence number.
This tool parses the module's AST, reads its ``__all__`` literal, and
requires each exported module-level function (the op surface — group
factories ``new_group``/``get_group`` are exempt, classes are skipped
naturally) to carry the ``@record_collective("<op>")`` decorator from
:mod:`paddle_tpu.observability.flight`.

Run directly (exit 1 on violations) or import ``check()`` — a tier-1
test wires it into the suite like ``check_fault_sites``, so a new
collective op cannot land silently untraced.
"""
from __future__ import annotations

import ast
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

#: exported names that are op *plumbing*, not collectives
EXEMPT = {"new_group", "get_group"}


def _default_path():
    return os.path.join(HERE, os.pardir, "paddle_tpu", "distributed",
                        "collective.py")


def _exported_names(tree):
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                return {elt.value for elt in node.value.elts
                        if isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)}
    return set()


def _decorator_name(dec):
    f = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _instrumented(fn):
    return any(_decorator_name(d) == "record_collective"
               for d in fn.decorator_list)


def check(path=None):
    """Return ['op (path:line): problem'] for uninstrumented ops."""
    path = os.path.abspath(path or _default_path())
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    exported = _exported_names(tree)
    rel = os.path.relpath(path, os.path.join(HERE, os.pardir))
    violations = []
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name not in exported or node.name in EXEMPT:
            continue
        if not _instrumented(node):
            violations.append(
                f"{node.name} ({rel}:{node.lineno}): public collective "
                f"op not routed through the flight recorder — add "
                f'@record_collective("{node.name}")')
    return violations


def main(argv=None):
    uncovered = check(argv[0] if argv else None)
    if uncovered:
        print("silently untraced collectives "
              "(see tools/check_collective_instrumented.py):",
              file=sys.stderr)
        for u in uncovered:
            print(f"  {u}", file=sys.stderr)
        return 1
    print("check_collective_instrumented: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
