#!/usr/bin/env python
"""Compatibility shim: the collective-instrumentation lint now lives
in the unified static-analysis framework as
:mod:`tools.analysis.passes.collective_instrumented` (rule id
``collective-instrumented``).  ``check()``/``main()`` keep their old
signatures and output format; run the whole suite with
``python -m tools.analysis``."""
from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analysis.passes.collective_instrumented import (  # noqa: E402,F401
    EXEMPT, check, find, main)

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
