#!/usr/bin/env python
"""Lint: no silent broad-exception swallows under paddle_tpu/.

A bare ``except Exception: pass`` is how silent corruption gets a
foothold: the failure the handler ate is exactly the evidence an
operator needed, and five PRs of resilience machinery (fault sites,
health anomalies, the integrity sentinel) are worthless for a failure
that never surfaces.  This tool walks every handler under the package
and flags any that

- catches **broadly** — a bare ``except:``, ``Exception`` or
  ``BaseException`` (alone or inside a tuple), and
- **does nothing** — a body of only ``pass`` / ``continue`` / ``break``
  / constant expressions (a string "comment" counts as nothing).

A flagged handler must log, re-raise, recover with real code, narrow
its exception list, or carry an explicit allowlist comment::

    except Exception:
        pass            # silent-ok: <why swallowing here is correct>

anywhere on its source lines.  The reason is mandatory — a naked
``silent-ok:`` is still a violation.  The genuine cleanup paths
(resource-tracker deregistration in ``io/multiprocess.py``,
interpreter-shutdown destructors, best-effort store key deletion) are
seeded with such comments; everything new must justify itself the same
way.

Run directly (exit 1 on violations) or import ``check()`` — a tier-1
test wires it into the suite so a new silent swallow cannot land.
"""
from __future__ import annotations

import ast
import os
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

MARKER = re.compile(r"#\s*silent-ok:\s*\S")

_BROAD = ("Exception", "BaseException")


def _iter_py(root):
    for dirpath, _, files in os.walk(root):
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def _catches_broadly(handler):
    t = handler.type
    if t is None:                           # bare except:
        return True

    def name_of(node):
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    if isinstance(t, ast.Tuple):
        return any(name_of(e) in _BROAD for e in t.elts)
    return name_of(t) in _BROAD


def _does_nothing(handler):
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue                        # docstring / ellipsis
        return False
    return True


def _allowlisted(handler, lines):
    last = max(getattr(s, "end_lineno", s.lineno) for s in handler.body)
    blob = "\n".join(lines[handler.lineno - 1:last])
    return bool(MARKER.search(blob))


def check(root=None):
    """Return ['relpath:lineno: except <what>'] for every silent broad
    swallow without a ``silent-ok:`` reason."""
    if root is None:
        root = os.path.join(HERE, os.pardir, "paddle_tpu")
    root = os.path.abspath(root)
    out = []
    for path in _iter_py(root):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        lines = src.splitlines()
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not (_catches_broadly(node) and _does_nothing(node)):
                continue
            if _allowlisted(node, lines):
                continue
            what = ("bare except" if node.type is None
                    else f"except {ast.unparse(node.type)}")
            rel = os.path.relpath(path, os.path.dirname(root))
            out.append(f"{rel}:{node.lineno}: {what}")
    return sorted(out)


def main(argv=None):
    bad = check()
    if bad:
        print("silent broad-exception swallows (log, re-raise, narrow "
              "the exception, or add '# silent-ok: <reason>'):",
              file=sys.stderr)
        for b in bad:
            print(f"  {b}", file=sys.stderr)
        return 1
    print("check_excepts: OK (no silent broad swallows)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
