#!/usr/bin/env python
"""Lint: every fault site registered under paddle_tpu/ must be
exercised by at least one test.

The resilience story rests on named fault sites
(``resilience.faults.fault_point``) being *killed at* by the
crash-consistency matrix — a site nobody injects is a recovery path
nobody has proven.  This tool collects every site name declared in the
package (positional ``fault_point("...")`` literals and ``site="..."``
keyword literals, e.g. ``atomic_write(..., site=...)``) and checks
that each name appears somewhere under tests/ — in an injected spec, a
``PADDLE_TPU_FAULTS`` string, or a generated worker script.

Keyword *defaults* (like ``atomic_write``'s ``site="io.write"``) are
declarations of a parameter, not registrations of a site, and are
skipped — call sites that rely on the default are linted at the
callee's own named sites.

Run directly (exit 1 on uncovered sites) or import ``check()`` — a
tier-1 test wires it into the suite so a new ``fault_point`` cannot
land without a test that fires it.
"""
from __future__ import annotations

import ast
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def _iter_py(root):
    for dirpath, _, files in os.walk(root):
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def collect_sites(root=None):
    """``{site_name: 'relpath:lineno'}`` for every literal fault site
    declared under ``root`` (default: the paddle_tpu package)."""
    if root is None:
        root = os.path.join(HERE, os.pardir, "paddle_tpu")
    root = os.path.abspath(root)
    sites = {}

    def note(name, path, lineno):
        rel = os.path.relpath(path, os.path.dirname(root))
        sites.setdefault(name, f"{rel}:{lineno}")

    for path in _iter_py(root):
        with open(path, encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fn_name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if fn_name == "fault_point" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                note(node.args[0].value, path, node.lineno)
            for kw in node.keywords:
                if kw.arg == "site" and \
                        isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, str):
                    note(kw.value.value, path, node.lineno)
    return sites


def covered_sites(sites, tests_root=None):
    """The subset of ``sites`` whose name appears in any test file."""
    if tests_root is None:
        tests_root = os.path.join(HERE, os.pardir, "tests")
    tests_root = os.path.abspath(tests_root)
    blob = []
    for path in _iter_py(tests_root):
        with open(path, encoding="utf-8") as f:
            blob.append(f.read())
    blob = "\n".join(blob)
    return {s for s in sites if s in blob}


def check(root=None, tests_root=None):
    """Return ['site (declared at path:line)'] for uncovered sites."""
    sites = collect_sites(root)
    covered = covered_sites(sites, tests_root)
    return [f"{name} (declared at {where})"
            for name, where in sorted(sites.items())
            if name not in covered]


def main(argv=None):
    uncovered = check()
    if uncovered:
        print("fault sites with no exercising test (add a matrix case "
              "in tests/, e.g. injected_faults(FaultSpec(site, ...))):",
              file=sys.stderr)
        for u in uncovered:
            print(f"  {u}", file=sys.stderr)
        return 1
    print(f"check_fault_sites: OK ({len(collect_sites())} sites covered)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
