#!/usr/bin/env python
"""Compatibility shim: the fault-site coverage lint now lives in the
unified static-analysis framework as
:mod:`tools.analysis.passes.fault_sites` (rule id ``fault-sites``).
``check()``/``collect_sites()``/``covered_sites()``/``main()`` keep
their old signatures; run the whole suite with
``python -m tools.analysis``."""
from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analysis.passes.fault_sites import (  # noqa: E402,F401
    check, collect_sites, covered_sites, find, main)

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
