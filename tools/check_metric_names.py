#!/usr/bin/env python
"""Lint: metric names registered under paddle_tpu/ must follow
Prometheus naming conventions.

A metrics surface is only useful if dashboards can rely on its shape:
``rate()`` over something not named ``*_total`` is a silent lie, a
camelCase name breaks every recording rule, and one name registered as
a counter here and a gauge there poisons the whole series.  Statically
scanned rules (literal first-argument names to ``Counter(`` /
``Gauge(`` / ``Histogram(`` and ``registry.counter(`` & co.):

- names are ``snake_case`` (``^[a-z][a-z0-9_]*$``);
- counter names end in ``_total``;
- a name never appears with two different metric kinds across the
  codebase;
- unit suffixes are canonical: a gauge or histogram name must not use
  an abbreviated unit (``_s``, ``_ms``, ``_secs``, ``_kb``, ``_pct``,
  ...) — spell it ``_seconds`` / ``_bytes`` / ``_ratio``;
- histograms always measure a quantity, so a histogram name must END
  in one of the canonical unit suffixes (a ``step_time`` histogram
  whose unit a dashboard has to guess is a recording-rule bug waiting
  to happen).  Unitless gauges (counts, 0/1 flags) stay suffix-free.

Run directly (exit 1 on violations) or import ``check()`` — a tier-1
test wires it into the suite like ``check_atomic_writes``, so a
nonconforming metric fails CI, not a dashboard review.
"""
from __future__ import annotations

import os
import re
import sys

# Counter("name"...) / Gauge( / Histogram(  — constructor form — and
# <registry>.counter("name"...) / .gauge( / .histogram( — get-or-create
# form.  Only literal names are checkable statically; a variable name
# is skipped (there are none today — keep it that way).
_METRIC_CALL = re.compile(
    r"""(?:\b(?P<cls>Counter|Gauge|Histogram)
         |\.(?P<meth>counter|gauge|histogram))
        \s*\(\s*(?P<q>['"])(?P<name>[^'"]+)(?P=q)""", re.VERBOSE)

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")

# canonical unit suffixes for quantity-bearing series
_UNIT_SUFFIXES = ("_seconds", "_bytes", "_ratio")
# abbreviated / non-canonical unit spellings that MUST NOT end a gauge
# or histogram name
_BAD_UNIT = re.compile(
    r"_(s|sec|secs|ms|millis|micros|us|ns|min|mins|minutes|hr|hrs|"
    r"hours|kb|mb|gb|tb|kib|mib|gib|pct|percent)$")


def check(root=None):
    """Return a list of 'path:line: problem' violations."""
    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "paddle_tpu")
    root = os.path.abspath(root)
    violations = []
    seen = {}                    # name -> (kind, "path:line")
    for dirpath, _, files in os.walk(root):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            rel = "paddle_tpu/" + \
                os.path.relpath(full, root).replace(os.sep, "/")
            with open(full, encoding="utf-8") as f:
                # strip per-line comments but keep the scan whole-file:
                # a call split across lines (Counter(\n  "name")) must
                # still be seen, since \s* matches the newline
                code = "\n".join(line.split("#", 1)[0]
                                 for line in f.read().splitlines())
            for m in _METRIC_CALL.finditer(code):
                kind = (m.group("cls") or m.group("meth")).lower()
                name = m.group("name")
                lineno = code.count("\n", 0, m.start()) + 1
                where = f"{rel}:{lineno}"
                if not _SNAKE.match(name):
                    violations.append(
                        f"{where}: metric name {name!r} is not "
                        "snake_case")
                if kind == "counter" and not name.endswith("_total"):
                    violations.append(
                        f"{where}: counter {name!r} must end in "
                        "'_total' (Prometheus convention)")
                if kind in ("gauge", "histogram"):
                    m_bad = _BAD_UNIT.search(name)
                    if m_bad:
                        violations.append(
                            f"{where}: {kind} {name!r} uses the "
                            f"non-canonical unit suffix "
                            f"'_{m_bad.group(1)}' — spell it out "
                            f"({'/'.join(_UNIT_SUFFIXES)})")
                    elif kind == "histogram" and \
                            not name.endswith(_UNIT_SUFFIXES):
                        violations.append(
                            f"{where}: histogram {name!r} must end in "
                            f"a canonical unit suffix "
                            f"({'/'.join(_UNIT_SUFFIXES)})")
                prev = seen.get(name)
                if prev is not None and prev[0] != kind:
                    violations.append(
                        f"{where}: {name!r} registered as {kind} "
                        f"but as {prev[0]} at {prev[1]} — one "
                        "name, one type")
                else:
                    seen.setdefault(name, (kind, where))
    return violations


def main(argv=None):
    violations = check(argv[0] if argv else None)
    if violations:
        print("metric naming violations "
              "(Prometheus conventions, see tools/check_metric_names.py):",
              file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("check_metric_names: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
