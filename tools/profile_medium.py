#!/usr/bin/env python
"""Component ablation profile of the gpt2-medium train step on the real
chip (VERDICT r4 item 4: raise MFU or commit a profile showing where the
time goes).

Measures, with the same dispatch-window/sync discipline as bench.py:
  full       — the complete train step (fwd + bwd + clip + Adam)
  grads      — value_and_grad only (no clip/Adam/param rebuild)
  fwd        — loss only
  no_flash   — full step with naive XLA attention instead of pallas
Prints one JSON line with the breakdown and derived component costs.
"""
from __future__ import annotations

import dataclasses
import json
import sys
import time

import numpy as np


def log(m):
    print(m, file=sys.stderr, flush=True)


def main(steps=8, warmup=2, batch=32, seq=1024, accum=4):
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.engine import EngineConfig, HybridEngine
    from paddle_tpu.models.gpt import GPT_CONFIGS

    try:
        jax.config.update("jax_compilation_cache_dir",
                          "/root/repo/.jax_bench_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          10.0)
    except Exception:
        pass

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 50304, (batch, seq)).astype(np.int32)
    labels = np.concatenate(
        [tokens[:, 1:], np.full((batch, 1), -100)], 1).astype(np.int32)

    def make_engine(use_flash=True):
        cfg = dataclasses.replace(GPT_CONFIGS["gpt2-medium"],
                                  use_flash=use_flash, remat="dots",
                                  dtype="bfloat16")
        return HybridEngine(cfg, devices=jax.devices()[:1],
                            engine_cfg=EngineConfig(accum_steps=accum))

    def time_steps(fn, sync, n=steps, w=warmup):
        fn()                       # compile
        sync()
        for _ in range(w):
            fn()
        sync()
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        sync()
        return (time.perf_counter() - t0) / n * 1e3

    results = {}

    # ---- full step (and reuse the engine for the sub-ablations) ----
    eng = make_engine(True)
    params, opt = eng.init(seed=0)
    state = {"p": params, "o": opt, "l": None}

    def full():
        state["p"], state["o"], state["l"] = eng.step(
            state["p"], state["o"], tokens, labels)

    results["full_ms"] = time_steps(full, lambda: float(state["l"]))
    log(f"full: {results['full_ms']:.1f} ms")

    # the grads/fwd ablations don't need the optimizer state — holding
    # its 4.3 GB alongside the grads program OOMs by ~60 MB
    state["o"] = None
    state["l"] = None

    # ---- grads only ----
    specs = eng.param_specs()

    def grads_local(params, tokens, labels):
        loss, g = jax.value_and_grad(eng._local_loss)(params, tokens,
                                                      labels, None)
        return loss, g

    from jax import shard_map as _sm

    # grads/fwd are measured per MICROBATCH (the step runs accum of
    # them under a scan) — the un-chunked full batch would hold 4x the
    # dots-remat residuals and compile-OOM
    mb = batch // accum
    tok_mb, lab_mb = tokens[:mb], labels[:mb]
    gfn = jax.jit(_sm(
        grads_local, mesh=eng.mesh,
        in_specs=(specs, eng.batch_spec(), eng.batch_spec()),
        out_specs=(P(), specs), check_vma=True))
    gl = {"l": None, "g": None}

    def grads():
        gl["l"], gl["g"] = gfn(state["p"], tok_mb, lab_mb)

    results["grads_micro_ms"] = time_steps(grads, lambda: float(gl["l"]))
    results["grads_ms"] = results["grads_micro_ms"] * accum
    log(f"grads: {results['grads_micro_ms']:.1f} ms/micro x {accum}")

    # ---- forward only ----
    ffn = jax.jit(_sm(
        lambda p, t, l: eng._local_loss(p, t, l, None), mesh=eng.mesh,
        in_specs=(specs, eng.batch_spec(), eng.batch_spec()),
        out_specs=P(), check_vma=True))
    fl = {"l": None}

    def fwd():
        fl["l"] = ffn(state["p"], tok_mb, lab_mb)

    results["fwd_micro_ms"] = time_steps(fwd, lambda: float(fl["l"]))
    results["fwd_ms"] = results["fwd_micro_ms"] * accum
    log(f"fwd: {results['fwd_micro_ms']:.1f} ms/micro x {accum}")

    # ---- naive attention full step ----
    state.clear()
    gl.clear()
    fl.clear()
    eng2 = make_engine(False)
    p2, o2 = eng2.init(seed=0)
    st2 = {"p": p2, "o": o2, "l": None}

    def full_naive():
        st2["p"], st2["o"], st2["l"] = eng2.step(
            st2["p"], st2["o"], tokens, labels)

    results["no_flash_ms"] = time_steps(full_naive,
                                        lambda: float(st2["l"]))
    log(f"no_flash: {results['no_flash_ms']:.1f} ms")

    results["derived"] = {
        "optimizer_and_clip_ms": results["full_ms"] - results["grads_ms"],
        "backward_ms": results["grads_ms"] - results["fwd_ms"],
        "flash_gain_ms": results["no_flash_ms"] - results["full_ms"],
    }
    tok = batch * seq
    results["tokens_per_sec"] = tok / (results["full_ms"] / 1e3)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
