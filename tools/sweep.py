#!/usr/bin/env python
"""Perf sweep on the real chip: batch size x remat policy x sync mode.

Prints one line per config; used to pick bench.py's default config.
"""
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(m):
    print(m, file=sys.stderr, flush=True)


def run_config(batch, remat, flash, async_steps, steps=10, warmup=2,
               seq=1024, accum=1):
    import jax

    from paddle_tpu.distributed.engine import EngineConfig, HybridEngine
    from paddle_tpu.models.gpt import GPT_CONFIGS

    cfg = dataclasses.replace(GPT_CONFIGS["gpt2-medium"], use_flash=flash,
                              remat=remat, dtype="bfloat16")
    eng = HybridEngine(cfg, devices=jax.devices()[:1],
                       engine_cfg=EngineConfig(accum_steps=accum))
    params, opt = eng.init(seed=0)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.concatenate(
        [tokens[:, 1:], np.full((batch, 1), -100)], 1).astype(np.int32)

    # NOTE: jax.block_until_ready returns WITHOUT waiting on the axon
    # tunnel backend; only a device->host value fetch truly syncs.
    t0 = time.perf_counter()
    params, opt, loss = eng.step(params, opt, tokens, labels)
    float(loss)
    compile_s = time.perf_counter() - t0

    for _ in range(warmup):
        params, opt, loss = eng.step(params, opt, tokens, labels)
    float(loss)

    t0 = time.perf_counter()
    if async_steps:
        for _ in range(steps):
            params, opt, loss = eng.step(params, opt, tokens, labels)
        float(loss)   # donation chains the steps; this waits for all of them
    else:
        for _ in range(steps):
            params, opt, loss = eng.step(params, opt, tokens, labels)
            float(loss)
    dt = (time.perf_counter() - t0) / steps
    tok_s = batch * seq / dt
    mfu = tok_s * (6 * 355e6 + 6 * 24 * seq * 1024) / 197e12
    log(f"bs={batch:3d} remat={remat:8s} flash={int(flash)} "
        f"async={int(async_steps)} accum={accum}: {dt*1e3:7.1f} ms/step "
        f"{tok_s:8.0f} tok/s mfu={mfu*100:.1f}% (compile {compile_s:.0f}s)")
    del params, opt
    return tok_s


if __name__ == "__main__":
    import jax

    log(f"devices={jax.devices()}")
    configs = [
        dict(batch=32, remat="dots", flash=True, async_steps=True, accum=4),
        dict(batch=16, remat="dots", flash=True, async_steps=True, accum=2),
        dict(batch=12, remat="dots", flash=True, async_steps=True),
        dict(batch=10, remat="dots", flash=True, async_steps=True),
        dict(batch=8, remat="dots_no_batch", flash=True, async_steps=True),
    ]
    for c in configs:
        try:
            run_config(**c)
        except Exception as e:
            log(f"{c}: FAILED {str(e)[:150]}")
